"""Batched Praos validation == sequential reference fold.

The contract (SURVEY.md §7.3 item 2): `validate_batch` must produce the
same resulting PraosState, the same valid-prefix length, and the same
first-error class as folding `praos.update` header by header.
"""

from dataclasses import replace
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ops.host import kes as host_kes
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import nonces, praos
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=50,
    kes_depth=3,
)


def make_chain(n, pools, params=PARAMS, epoch_nonce=b"\x07" * 32, lview=None):
    """Leader-aware forging: only slots some pool actually wins."""
    if lview is None:
        lview = fixtures.make_ledger_view(pools)
    hvs = []
    prev = None
    slot = 1
    while len(hvs) < n:
        pool = fixtures.find_leader(params, pools, lview, slot, epoch_nonce)
        if pool is not None:
            hv = fixtures.forge_header_view(
                params, pool, slot=slot, epoch_nonce=epoch_nonce,
                prev_hash=prev, body_bytes=b"body-%d" % len(hvs),
            )
            hvs.append(hv)
            prev = (b"%032d" % len(hvs))[:32]
        slot += 1
    return hvs


def sequential_fold(params, ticked, hvs):
    """Reference semantics: fold praos.update, stop at first error."""
    st = ticked.state
    for i, hv in enumerate(hvs):
        try:
            st = praos.update(params, hv, hv.slot, praos.TickedPraosState(st, ticked.ledger_view))
        except praos.PraosValidationError as e:
            return st, i, e
    return st, len(hvs), None


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth) for i in range(3)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


def ticked_state(lview, epoch_nonce=b"\x07" * 32):
    st = praos.PraosState(epoch_nonce=epoch_nonce)
    return praos.TickedPraosState(st, lview)


def assert_same(params, ticked, hvs):
    st_seq, n_seq, err_seq = sequential_fold(params, ticked, hvs)
    res = pbatch.validate_batch(params, ticked, hvs)
    assert res.n_valid == n_seq
    if err_seq is None:
        assert res.error is None
    else:
        assert type(res.error) is type(err_seq)
    assert res.state == replace(
        st_seq, ocert_counters=dict(st_seq.ocert_counters)
    ) or (
        res.state.evolving_nonce == st_seq.evolving_nonce
        and res.state.candidate_nonce == st_seq.candidate_nonce
        and res.state.lab_nonce == st_seq.lab_nonce
        and res.state.last_slot == st_seq.last_slot
        and dict(res.state.ocert_counters) == dict(st_seq.ocert_counters)
    )


@pytest.mark.slow
def test_all_valid(pools, lview):
    hvs = make_chain(8, pools)
    t = ticked_state(lview)
    assert_same(PARAMS, t, hvs)
    res = pbatch.validate_batch(PARAMS, t, hvs)
    assert res.n_valid == 8 and res.error is None


def test_mixed_proof_format_chain_validates(pools, lview, monkeypatch):
    """A chain mixing 80-byte draft-03 and 128-byte batch-compatible
    proofs (e.g. synthesized across an OCT_VRF_BATCH flip) validates
    header-by-header like the reference fold instead of crashing the
    uniform-proof-column staging: validate_batch segments the run at
    format boundaries. Native backend — no device compile, fast tier."""
    eta = b"\x07" * 32
    hvs, prev, slot = [], None, 1
    while len(hvs) < 6:
        pool = fixtures.find_leader(PARAMS, pools, lview, slot, eta)
        if pool is not None:
            monkeypatch.setenv("OCT_VRF_BATCH",
                               "0" if len(hvs) % 2 else "1")
            hv = fixtures.forge_header_view(
                PARAMS, pool, slot=slot, epoch_nonce=eta,
                prev_hash=prev, body_bytes=b"body-%d" % len(hvs),
            )
            hvs.append(hv)
            prev = (b"%032d" % len(hvs))[:32]
        slot += 1
    monkeypatch.delenv("OCT_VRF_BATCH", raising=False)
    assert {len(hv.vrf_proof) for hv in hvs} == {80, 128}
    t = ticked_state(lview)
    st_seq, n_seq, err_seq = sequential_fold(PARAMS, t, hvs)
    assert err_seq is None and n_seq == len(hvs)
    res = pbatch.validate_batch(PARAMS, t, hvs, backend="native")
    assert res.error is None and res.n_valid == len(hvs)
    assert res.state.evolving_nonce == st_seq.evolving_nonce
    assert dict(res.state.ocert_counters) == dict(st_seq.ocert_counters)
    # a tampered mixed-format lane still isolates with the exact error
    bad = hvs[4]
    hvs[4] = replace(
        bad,
        vrf_proof=bad.vrf_proof[:-1] + bytes([bad.vrf_proof[-1] ^ 1]),
    )
    res = pbatch.validate_batch(PARAMS, t, hvs, backend="native")
    assert res.n_valid == 4
    assert isinstance(res.error, praos.VRFKeyBadProof)


@pytest.mark.slow
def test_bad_kes_sig_midway(pools, lview):
    hvs = make_chain(6, pools)
    bad = hvs[3]
    hvs[3] = replace(bad, kes_sig=b"\x01" + bad.kes_sig[1:])
    assert_same(PARAMS, ticked_state(lview), hvs)


@pytest.mark.slow
def test_bad_vrf_proof(pools, lview):
    hvs = make_chain(5, pools)
    bad = hvs[2]
    hvs[2] = replace(bad, vrf_proof=bad.vrf_proof[:-1] + bytes([bad.vrf_proof[-1] ^ 1]))
    assert_same(PARAMS, ticked_state(lview), hvs)


@pytest.mark.slow
def test_bad_ocert_sigma(pools, lview):
    hvs = make_chain(4, pools)
    bad = hvs[1]
    hvs[1] = replace(bad, ocert=replace(bad.ocert, sigma=bytes(64)))
    assert_same(PARAMS, ticked_state(lview), hvs)


@pytest.mark.slow
def test_unknown_pool(pools, lview):
    stranger = fixtures.make_pool(99, kes_depth=PARAMS.kes_depth)
    hvs = make_chain(3, pools)
    hvs[1] = fixtures.forge_header_view(
        PARAMS, stranger, slot=hvs[1].slot, epoch_nonce=b"\x07" * 32,
        prev_hash=hvs[1].prev_hash,
    )
    assert_same(PARAMS, ticked_state(lview), hvs)


@pytest.mark.slow
def test_counter_regression(pools, lview):
    # same pool twice: second header reuses a LOWER ocert counter; pick
    # slots the pool actually wins so the counter check is what fires
    p = pools[0]
    eta = b"\x07" * 32
    slots = [
        s for s in range(1, 2000)
        if fixtures.find_leader(PARAMS, [p], lview, s, eta) is not None
    ][:2]
    assert len(slots) == 2
    hv1 = fixtures.forge_header_view(
        PARAMS, p, slot=slots[0], epoch_nonce=eta, prev_hash=None,
        ocert_counter=5,
    )
    hv2 = fixtures.forge_header_view(
        PARAMS, p, slot=slots[1], epoch_nonce=eta, prev_hash=b"x" * 32,
        ocert_counter=3,
    )
    assert_same(PARAMS, ticked_state(lview), [hv1, hv2])


@pytest.mark.slow
def test_leader_threshold_losers(pools):
    # tiny stake for pool 0 => its VRF values should mostly lose the slot
    lv = fixtures.make_ledger_view(
        pools, stakes=[Fraction(1, 10**12)] + [Fraction(1, 2)] * (len(pools) - 1)
    )
    hvs = make_chain(6, pools)
    t = ticked_state(lv)
    assert_same(PARAMS, t, hvs)


@pytest.mark.slow
def test_validate_chain_epoch_segmentation(pools, lview):
    # headers crossing an epoch boundary (epoch_length=50): nonce rotation
    # between segments must match the sequential tick-per-header fold
    params = PARAMS
    hvs = []
    prev = None
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)

    # build chain with correct per-epoch nonces by running the fold as forge
    st = st0
    slot = 44  # will cross slot 50 (epoch 0 -> 1)
    while len(hvs) < 8:
        ticked = praos.tick(params, lview, slot, st)
        pool = fixtures.find_leader(
            params, pools, lview, slot, ticked.state.epoch_nonce
        )
        if pool is None:
            slot += 1
            continue
        hv = fixtures.forge_header_view(
            params, pool, slot=slot,
            epoch_nonce=ticked.state.epoch_nonce, prev_hash=prev,
            body_bytes=b"b%d" % len(hvs),
        )
        st = praos.update(params, hv, slot, ticked)
        hvs.append(hv)
        prev = (b"%032d" % len(hvs))[:32]
        slot += 1

    res = pbatch.validate_chain(
        params, lambda epoch: lview, st0, hvs
    )
    assert res.error is None and res.n_valid == len(hvs)
    assert res.state.evolving_nonce == st.evolving_nonce
    assert res.state.epoch_nonce == st.epoch_nonce
    assert res.state.candidate_nonce == st.candidate_nonce


def test_leader_threshold_bracket_sane():
    lo, hi = pbatch.leader_threshold_bracket(Fraction(1, 3), Fraction(1, 20))
    assert 0 < lo <= hi < pbatch.leader.LEADER_VALUE_MAX
    assert hi - lo <= 1 << 200  # tight bracket (width << 2^256)
    assert pbatch.leader_threshold_bracket(Fraction(0), Fraction(1, 20)) == (0, 0)


def test_staged_relayout_matches_pk_arrays(monkeypatch):
    """verify_praos_staged (the PRODUCTION dispatch marshalling) must
    hand verify_praos_tiles EXACTLY what the host-side pk_arrays built —
    column for column, dtype for dtype. Captures the tiles call's real
    arguments instead of re-implementing the relayout, so a swapped
    argument in the staged entry fails here."""
    import functools

    import numpy as np

    from ouroboros_consensus_tpu.ops.pk import kernels as K

    # this test pins the DRAFT-03 (80-byte proof) staged wiring; the
    # batch-compatible twin is test_split_dispatch_bc below
    monkeypatch.setenv("OCT_VRF_BATCH", "0")
    pools = [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth)
             for i in range(3)]
    lview = fixtures.make_ledger_view(pools)
    hvs = make_chain(24, pools, lview=lview)
    pre = pbatch.host_prechecks(PARAMS, lview, hvs)
    staged = pbatch.stage(PARAMS, lview, b"\x07" * 32, hvs, pre.kes_evolution)
    assert not pbatch.batch_is_bc(staged)
    ref = pbatch.pk_arrays(staged)

    captured = {}

    def capture(*args, kes_depth):
        captured["args"] = args
        captured["kes_depth"] = kes_depth
        return None

    monkeypatch.setattr(K, "verify_praos_tiles", capture)
    ed, kes, vrf = staged.ed, staged.kes, staged.vrf
    K.verify_praos_staged(
        ed.pk, ed.r, ed.s, ed.hblocks, ed.hnblocks,
        kes.vk, kes.period, kes.r, kes.s, kes.vk_leaf, kes.siblings,
        kes.hblocks, kes.hnblocks,
        vrf.pk, vrf.gamma, vrf.c, vrf.s, vrf.alpha,
        staged.beta, staged.thr_lo, staged.thr_hi,
        kes_depth=PARAMS.kes_depth,
    )
    got = captured["args"]
    assert captured["kes_depth"] == PARAMS.kes_depth
    assert len(ref) == len(got) == 21
    for i, (a, b) in enumerate(zip(ref, got)):
        a, b = np.asarray(a), np.asarray(b)
        assert a.shape == b.shape and a.dtype == b.dtype == np.int32, i
        assert (a == b).all(), i


def test_split_dispatch_threads_stages_correctly(monkeypatch):
    """verify_praos_split (the per-stage-jit production dispatch,
    VERDICT r3 item 2) must hand each STAGE exactly the columns the
    fused composition would: the real relayout jit runs, the crypto
    stages are capture stubs returning shaped dummies, and every
    captured argument is checked against pk_arrays — so a swapped
    argument in the split wiring fails here without a multi-minute
    XLA:CPU crypto compile."""
    import numpy as np
    from jax import numpy as jnp

    from ouroboros_consensus_tpu.ops.pk import kernels as K

    monkeypatch.setenv("OCT_VRF_BATCH", "0")  # draft-03 wiring pin
    pools = [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth)
             for i in range(3)]
    lview = fixtures.make_ledger_view(pools)
    hvs = make_chain(8, pools, lview=lview)
    pre = pbatch.host_prechecks(PARAMS, lview, hvs)
    staged = pbatch.stage(PARAMS, lview, b"\x07" * 32, hvs, pre.kes_evolution)
    assert not pbatch.batch_is_bc(staged)
    ref = [np.asarray(a) for a in pbatch.pk_arrays(staged)]
    b = staged.beta.shape[0]
    depth = PARAMS.kes_depth

    captured = {}

    def stub(name, outs):
        def fn(*args):
            captured[name] = [np.asarray(a) for a in args]
            return tuple(jnp.zeros((*p, b), jnp.int32) for p in outs)
        return fn

    monkeypatch.setitem(K._SPLIT_JIT, "ed", stub("ed", [(1,), (80,)]))
    monkeypatch.setitem(
        K._SPLIT_JIT, ("kes", depth), stub("kes", [(1,), (80,)])
    )
    monkeypatch.setitem(K._SPLIT_JIT, "vrf", stub("vrf", [(1,), (400,)]))
    monkeypatch.setitem(
        K._SPLIT_JIT, "finish", stub("finish", [(5,), (32,), (32,)])
    )

    ed, kes, vrf = staged.ed, staged.kes, staged.vrf
    out = K.verify_praos_split(
        ed.pk, ed.r, ed.s, ed.hblocks, ed.hnblocks,
        kes.vk, kes.period, kes.r, kes.s, kes.vk_leaf, kes.siblings,
        kes.hblocks, kes.hnblocks,
        vrf.pk, vrf.gamma, vrf.c, vrf.s, vrf.alpha,
        staged.beta, staged.thr_lo, staged.thr_hi,
        kes_depth=depth,
    )
    assert len(out) == 3  # finish's (flags, eta, leader_value)

    # ref index map (pk_arrays order):
    # 0 ed_pk 1 ed_r 2 ed_s 3 ed_hb 4 ed_hnb 5 kes_vk 6 kes_per 7 kes_r
    # 8 kes_s 9 kes_leaf 10 kes_sib 11 kes_hb 12 kes_hnb 13 vrf_pk
    # 14 vrf_g 15 vrf_c 16 vrf_s 17 vrf_al 18 beta 19 tlo 20 thi
    def eq(got, want_ix):
        assert (got == ref[want_ix]).all(), want_ix

    g = captured["ed"]
    eq(g[0], 0); eq(g[1], 2); eq(g[2], 3); eq(g[3], 4)
    g = captured["kes"]
    eq(g[0], 5); eq(g[1], 6); eq(g[2], 8); eq(g[3], 9); eq(g[4], 10)
    eq(g[5], 11); eq(g[6], 12)
    g = captured["vrf"]
    eq(g[0], 13); eq(g[1], 14); eq(g[2], 15); eq(g[3], 16); eq(g[4], 17)
    g = captured["finish"]
    # finish(ed_ok, ed_pt, ed_r, kes_ok, kes_pt, kes_r, vrf_ok, vrf_pts,
    #        c, beta, thr_lo, thr_hi)
    eq(g[2], 1); eq(g[5], 7); eq(g[8], 15); eq(g[9], 18)
    eq(g[10], 19); eq(g[11], 20)
    assert g[0].shape == (1, b) and g[1].shape == (80, b)
    assert g[6].shape == (1, b) and g[7].shape == (400, b)


def test_split_dispatch_bc_threads_stages_correctly(monkeypatch):
    """The batch-compatible split wiring (relayout_bc -> ed/kes ->
    vrf_bc -> finish): announced u/v columns reach the vrf_bc stage, and
    the finish stage receives the DERIVED challenge (the vrf_bc stage's
    second output), not a staged column."""
    import numpy as np
    from jax import numpy as jnp

    from ouroboros_consensus_tpu.ops.pk import kernels as K

    pools = [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth)
             for i in range(3)]
    lview = fixtures.make_ledger_view(pools)
    hvs = make_chain(8, pools, lview=lview)
    assert len(hvs[0].vrf_proof) == 128  # forge default is bc
    pre = pbatch.host_prechecks(PARAMS, lview, hvs)
    staged = pbatch.stage(PARAMS, lview, b"\x07" * 32, hvs, pre.kes_evolution)
    assert pbatch.batch_is_bc(staged)
    ref = [np.asarray(a) for a in pbatch.pk_arrays(staged)]
    b = staged.beta.shape[0]
    depth = PARAMS.kes_depth

    captured = {}

    def stub(name, outs):
        def fn(*args):
            captured[name] = [np.asarray(a) for a in args]
            return tuple(jnp.zeros((*p, b), jnp.int32) for p in outs)
        return fn

    monkeypatch.setitem(K._SPLIT_JIT, "ed", stub("ed", [(1,), (80,)]))
    monkeypatch.setitem(
        K._SPLIT_JIT, ("kes", depth), stub("kes", [(1,), (80,)])
    )
    monkeypatch.setitem(
        K._SPLIT_JIT, "vrf_bc", stub("vrf_bc", [(1,), (16,), (400,)])
    )
    monkeypatch.setitem(
        K._SPLIT_JIT, "finish", stub("finish", [(5,), (32,), (32,)])
    )

    ed, kes, vrf = staged.ed, staged.kes, staged.vrf
    out = K.verify_praos_split_bc(
        ed.pk, ed.r, ed.s, ed.hblocks, ed.hnblocks,
        kes.vk, kes.period, kes.r, kes.s, kes.vk_leaf, kes.siblings,
        kes.hblocks, kes.hnblocks,
        vrf.pk, vrf.gamma, vrf.u, vrf.v, vrf.s, vrf.alpha,
        staged.beta, staged.thr_lo, staged.thr_hi,
        kes_depth=depth,
    )
    assert len(out) == 3

    # bc pk_arrays index map: 0-12 as draft-03, then 13 vrf_pk 14 vrf_g
    # 15 vrf_u 16 vrf_v 17 vrf_s 18 vrf_al 19 beta 20 tlo 21 thi
    def eq(got, want_ix):
        assert (got == ref[want_ix]).all(), want_ix

    g = captured["vrf_bc"]
    eq(g[0], 13); eq(g[1], 14); eq(g[2], 15); eq(g[3], 16); eq(g[4], 17)
    eq(g[5], 18)
    g = captured["finish"]
    eq(g[2], 1); eq(g[5], 7); eq(g[9], 19); eq(g[10], 20); eq(g[11], 21)
    # the challenge column handed to finish is the vrf_bc stage's c16
    # output (a stub zero array here), NOT any staged column
    assert g[8].shape == (16, b) and (g[8] == 0).all()
    assert g[0].shape == (1, b) and g[1].shape == (80, b)
    assert g[6].shape == (1, b) and g[7].shape == (400, b)


@pytest.mark.slow
def test_validate_chain_cross_epoch_pipelining(pools, lview):
    # THREE epoch boundaries with several small batches per epoch and
    # pipeline depth 3: the next epoch's first windows must stage with
    # the LOOKAHEAD nonce (combine(candidate, last_epoch_block_nonce)
    # once the fold passes the freeze slot) while the current epoch's
    # tail is still in flight — the retire-time tick asserts the staged
    # nonce, and the final state must equal the per-header fold.
    params = PARAMS
    hvs = []
    prev = None
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)

    st = st0
    slot = 2
    while len(hvs) < 70:
        ticked = praos.tick(params, lview, slot, st)
        pool = fixtures.find_leader(
            params, pools, lview, slot, ticked.state.epoch_nonce
        )
        if pool is None:
            slot += 1
            continue
        hv = fixtures.forge_header_view(
            params, pool, slot=slot,
            epoch_nonce=ticked.state.epoch_nonce, prev_hash=prev,
            body_bytes=b"c%d" % len(hvs),
        )
        st = praos.update(params, hv, slot, ticked)
        hvs.append(hv)
        prev = (b"%032d" % len(hvs))[:32]
        slot += 1
    assert params.epoch_of(hvs[-1].slot) >= 3  # crossed >= 3 boundaries

    res = pbatch.validate_chain(
        params, lambda epoch: lview, st0, hvs, max_batch=4,
        pipeline_depth=3,
    )
    assert res.error is None and res.n_valid == len(hvs)
    assert res.state == st
