"""octwall tier-1 gate (Pass 4): compile-cost feature extraction, the
fitted model + its pinned calibration (the within-2x acceptance), the
compile_wall ratchet + pathology advisories, the registry drift gate,
and the bench pre-flight refusal path (stubbed clock + a real
dispatch_batch window riding the fallback with the refusal recorded in
the warmup report)."""

import json
import os
import time
from dataclasses import replace
from fractions import Fraction

import numpy as np
import pytest

import jax
from jax import lax, numpy as jnp

from ouroboros_consensus_tpu.analysis import absint, costmodel, graphs
from ouroboros_consensus_tpu.obs.warmup import WARMUP, WarmupRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sds(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _unfenced_chain(depth):
    """The pre-PR-1 pathology shape: an unrolled multiply chain the
    algebraic simplifier's rewrite loop chews on superlinearly."""

    def fn(x):
        for _ in range(depth):
            x = x * x + x
        return x

    return fn


def _fenced_chain(depth):
    """The PR-1 remediation twin: the same chain behind a fori_loop
    fence (one small body computation, chain depth flat)."""

    def fn(x):
        return lax.fori_loop(0, depth, lambda _, v: v * v + v, x)

    return fn


@pytest.fixture
def fresh_warmup():
    """Snapshot-and-restore the process-wide warmup recorder around a
    test that records refusals/stages into it."""
    WARMUP.reset()
    yield WARMUP
    WARMUP.reset()


# ---------------------------------------------------------------------------
# Feature extraction
# ---------------------------------------------------------------------------


def test_feature_extraction_counts_the_chain():
    f = costmodel.extract_features(
        jax.make_jaxpr(_unfenced_chain(50))(_sds(8)), "u"
    )
    assert f.eqns == 100
    assert f.mul_chain_depth == 50
    assert f.mul_count == 50
    assert f.computations == 1
    assert f.max_comp_eqns == 100
    assert f.fence_count == 0
    # the advisory provenance names THIS file
    assert "test_costmodel" in f.chain_src


def test_fence_resets_chain_and_attributes_the_body():
    f = costmodel.extract_features(
        jax.make_jaxpr(_fenced_chain(50))(_sds(8)), "f"
    )
    assert f.fence_count >= 1
    assert f.mul_chain_depth <= 3
    assert f.computations >= 2
    assert f.max_body_eqns >= 2
    # the monolith here IS the fence body, attributed to its source eqn
    assert f.monolith_src.startswith(("scan@", "while@", "pjit@"))


def test_features_match_pass2_metrics_on_a_registry_graph():
    """The cost walk mirrors graphs._analyze semantics: shared metrics
    must agree exactly on a real (small) registry graph."""
    tr = graphs.trace_graph("verdict_reduce")
    r = graphs.analyze_jaxpr(tr, "verdict_reduce")
    f = costmodel.extract_features(tr, "verdict_reduce")
    assert f.eqns == r.eqns
    assert f.computations == r.computations
    assert f.mul_chain_depth == r.mul_chain_depth
    assert f.op_fanout == r.op_fanout
    assert f.remat_width == r.remat_width


def test_feature_hash_stable_and_structure_sensitive():
    f = costmodel.extract_features(
        jax.make_jaxpr(_unfenced_chain(20))(_sds(8)), "a"
    )
    g = costmodel.extract_features(
        jax.make_jaxpr(_unfenced_chain(20))(_sds(8)), "b"
    )
    assert f.hash() == g.hash()  # name does not enter the hash
    h = costmodel.extract_features(
        jax.make_jaxpr(_unfenced_chain(21))(_sds(8)), "a"
    )
    assert f.hash() != h.hash()


# ---------------------------------------------------------------------------
# The fitted model + pinned calibration (the within-2x acceptance)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cost_json():
    return costmodel.load_cost()


def test_shipped_model_is_monotone_nonnegative(cost_json):
    model = cost_json["model"]
    assert model["rows"] >= 3
    for k, v in model["coeffs"].items():
        assert v >= 0, f"negative coefficient on {k}"
    # monotone: more structure never predicts a cheaper compile
    small = {k: 100 for k in costmodel.FEATURE_NAMES}
    big = {k: 10_000 for k in costmodel.FEATURE_NAMES}
    assert costmodel.predict(big, model) >= costmodel.predict(small, model)


def test_shipped_pins_are_consistent_with_the_model(cost_json):
    model = cost_json["model"]
    for name, pin in cost_json["graphs"].items():
        assert pin["feature_hash"] == costmodel.feature_hash(
            pin["features"]
        ), f"{name}: pinned hash does not match pinned features"
        pred = costmodel.predict(pin["features"], model)
        assert pin["predicted_s"] == round(pred, 1), \
            f"{name}: predicted_s pin is stale (re-run fit/--update-costs)"


def test_calibration_within_2x_on_80_percent(cost_json):
    """The acceptance criterion, validated offline from the pinned
    calibration rows (the same check `fit_costmodel.py --check` runs):
    predicted cold-compile wall within 2x of the measured first-execute
    on >= 80% of calibrated stages."""
    model = cost_json["model"]
    rows = cost_json["calibration"]
    assert len(rows) >= 10
    ok = 0
    for r in rows:
        ratio = costmodel.predict(r["features"], model) / max(
            1e-3, r["measured_s"]
        )
        ok += 0.5 <= ratio <= 2.0
    assert ok / len(rows) >= 0.8, f"only {ok}/{len(rows)} within 2x"


def test_every_registered_graph_is_pinned(cost_json):
    missing = set(graphs.registered_graphs()) - set(cost_json["graphs"])
    assert missing == set()


def test_fit_model_recovers_a_size_law():
    rows = [
        ({"eqns": e, "computations": 1, "max_comp_eqns": e,
          "mul_chain_depth": e // 2, "max_body_eqns": 0, "dot_count": 0},
         0.05 + e / 1000)
        for e in (100, 400, 1600, 6400, 25600)
    ]
    m = costmodel.fit_model(rows, backend="test")
    assert all(v >= 0 for v in m["coeffs"].values())
    for f, w in rows:
        assert 0.5 <= costmodel.predict(f, m) / w <= 2.0


def test_unfenced_chain_predicts_far_costlier_than_fenced_twin():
    """Regression fixture pinning the PR-1 remediation from the model
    side: the pre-remediation unfenced-multiply-chain shape must be
    predicted HIGH cost and its fori_loop-fenced twin LOW — if the
    model cannot separate them, the fit is meaningless."""
    unfenced = costmodel.extract_features(
        jax.make_jaxpr(_unfenced_chain(600))(_sds(32)), "unfenced"
    )
    fenced = costmodel.extract_features(
        jax.make_jaxpr(_fenced_chain(600))(_sds(32)), "fenced"
    )
    pu = costmodel.predict(unfenced)
    pf = costmodel.predict(fenced)
    assert pu is not None and pf is not None
    assert pu >= 3.0 * pf, (pu, pf)


# ---------------------------------------------------------------------------
# compile_wall ratchet + advisories
# ---------------------------------------------------------------------------


def test_check_compile_wall_flags_over_and_missing():
    f = costmodel.extract_features(
        jax.make_jaxpr(_unfenced_chain(600))(_sds(32)), "g"
    )
    budgets = {"compile_wall": {"graphs": {"g": {"predicted_s_max": 1e-6}}}}
    v = costmodel.check_compile_wall([f], budgets)
    assert len(v) == 1 and "exceeds budget" in v[0]
    assert costmodel.check_compile_wall([f], {"compile_wall": {}})
    ok = {"compile_wall": {"graphs": {"g": {"predicted_s_max": 1e9}}}}
    assert costmodel.check_compile_wall([f], ok) == []


def test_advisories_name_the_source_to_split():
    f = costmodel.extract_features(
        jax.make_jaxpr(_unfenced_chain(300))(_sds(32)), "g"
    )
    budgets = {"compile_wall": {"advisory": {
        "monolith_eqns": 100, "unfenced_chain": 100,
    }}}
    adv = costmodel.advisories(f, budgets)
    assert len(adv) == 2
    assert any("monolith computation" in a and "fence" in a for a in adv)
    assert any("unfenced multiply chain" in a and "test_costmodel" in a
               for a in adv)
    # a wall violation carries its advisories inline
    budgets["compile_wall"]["graphs"] = {"g": {"predicted_s_max": 1e-6}}
    v = costmodel.check_compile_wall([f], budgets)
    assert "unfenced multiply chain" in v[0]
    # and the detector fires on its own even when the wall fits
    budgets["compile_wall"]["graphs"] = {"g": {"predicted_s_max": 1e9}}
    v = costmodel.check_compile_wall([f], budgets)
    assert len(v) == 2
    assert all(x.startswith("g: ") for x in v)


def test_shipped_budgets_have_a_compile_wall_section():
    budgets = graphs.load_budgets()
    sec = budgets["compile_wall"]
    missing = set(graphs.registered_graphs()) - set(sec["graphs"])
    assert missing == set()
    assert sec["advisory"]["unfenced_chain"] >= 160  # over current max
    for name, cfg in sec["graphs"].items():
        assert cfg["predicted_s_max"] > 0


# ---------------------------------------------------------------------------
# Registry drift gate
# ---------------------------------------------------------------------------


def test_registry_drift_gate_clean_today():
    assert absint.check_registry_drift() == []


def test_registry_drift_gate_seeded(monkeypatch):
    """Seed the drift: a REGISTRY entry with no shapes.json spec and no
    GRAPH_SOURCES mapping must produce BOTH loud violations (it used to
    surface only as a KeyError deep inside certification)."""
    monkeypatch.setitem(graphs.REGISTRY, "ghost_graph", lambda t=None: None)
    v = absint.check_registry_drift()
    assert any("ghost_graph" in x and "shapes.json" in x for x in v)
    assert any("ghost_graph" in x and "GRAPH_SOURCES" in x for x in v)
    # aux drift is gated the same way
    monkeypatch.setitem(absint.AUX_REGISTRY, "ghost_aux",
                        lambda t=None: None)
    v = absint.check_registry_drift()
    assert any("ghost_aux" in x and "AUX_SOURCES" in x for x in v)


# ---------------------------------------------------------------------------
# Stage-name resolution + warmup note hashes
# ---------------------------------------------------------------------------


def test_stage_graph_resolution():
    assert costmodel.stage_graph("ed@b8192") == "ed_core"
    assert costmodel.stage_graph("agg-packed:304b:scan") == "aggregate_core"
    assert costmodel.stage_graph("xla-packed:304b:p128:scan") == \
        "verify_praos_core_bc"
    # draft-03 packed windows resolve to the NON-bc composed twin
    assert costmodel.stage_graph("xla-packed:256b:p80:noscan") == \
        "verify_praos_core"
    assert costmodel.stage_graph("unpack_a1b2c3@b8192") == "packed_unpack"
    assert costmodel.stage_graph("reduce_noscan@b64") == "verdict_reduce"
    assert costmodel.stage_graph("something-new") is None


def test_check_pins_flags_drift_and_missing():
    """The one-sidedness closer: a graph whose current structure drifts
    from its costmodel.json pin (or has no pin) must fail the lint cost
    pass, so stage notes can never stamp walls with a stale hash."""
    pin = costmodel.pinned("packed_unpack")
    fresh = costmodel.CostFeatures(name="packed_unpack",
                                   **{k: pin["features"][k]
                                      for k in costmodel.FEATURE_NAMES})
    assert costmodel.check_pins([fresh]) == []
    drifted = costmodel.CostFeatures(name="packed_unpack",
                                     **{k: pin["features"][k]
                                        for k in costmodel.FEATURE_NAMES})
    drifted.eqns += 1
    (v,) = costmodel.check_pins([drifted])
    assert "drifted" in v and "--update-costs" in v
    ghost = costmodel.CostFeatures(name="no_such_graph")
    (v,) = costmodel.check_pins([ghost])
    assert "no costmodel.json pin" in v


def test_stage_feature_hash_joins_to_the_pin():
    pin = costmodel.pinned("ed_core")
    assert costmodel.stage_feature_hash("ed@b8192") == pin["feature_hash"]
    assert costmodel.stage_feature_hash("no-such-stage") is None


def test_warmup_note_carries_hash_and_refusals_flush(tmp_path,
                                                     monkeypatch):
    path = str(tmp_path / "wr.json")
    monkeypatch.setenv("OCT_WARMUP_REPORT", path)
    w = WarmupRecorder()
    w.note_stage("ed@b8", 1.5, via="jit", feature_hash="abcd1234")
    w.note_refusal("agg-packed:304b:scan", 410.0, 90.0,
                   action="stage-split-fallback", detail="graph=aggregate_core")
    rep = json.load(open(path))
    assert rep["stages"]["ed@b8"]["feature_hash"] == "abcd1234"
    (ref,) = rep["refusals"]
    assert ref["stage"] == "agg-packed:304b:scan"
    assert ref["predicted_s"] == 410.0
    assert ref["remaining_s"] == 90.0
    assert ref["action"] == "stage-split-fallback"
    w.reset()
    assert w.report()["refusals"] == []


# ---------------------------------------------------------------------------
# Pre-flight admission gate (stubbed clock)
# ---------------------------------------------------------------------------


def test_preflight_admits_without_deadline(monkeypatch, fresh_warmup):
    monkeypatch.delenv("OCT_WALL_DEADLINE", raising=False)
    assert costmodel.preflight("agg-packed:304b:scan") is True
    assert fresh_warmup.report()["refusals"] == []


def test_preflight_refuses_cold_overbudget_and_records(monkeypatch,
                                                       fresh_warmup):
    """The bench attempt gate, stubbed clock: predicted 410 s against
    90 s of remaining wall -> refused, and the refusal is IN the warmup
    report (the round JSON banks the decision)."""
    monkeypatch.setenv("OCT_WALL_DEADLINE", "1090.0")
    monkeypatch.setattr(costmodel, "predicted_wall", lambda g: 410.0)
    stage = "agg-packed:304b:scan"
    assert costmodel.preflight(stage, now=1000.0) is False
    (ref,) = fresh_warmup.report()["refusals"]
    assert ref["stage"] == stage
    assert ref["predicted_s"] == 410.0
    assert ref["remaining_s"] == 90.0
    assert "aggregate_core" in ref["detail"]
    # plenty of remaining wall -> admitted, no second refusal
    assert costmodel.preflight(stage, now=1090.0 - 500.0) is True
    assert len(fresh_warmup.report()["refusals"]) == 1


def test_preflight_admits_warm_stage_even_overbudget(monkeypatch,
                                                     fresh_warmup):
    """A stage that already recorded its first execute owes no compile:
    the gate must not refuse warm dispatches at the end of the wall."""
    monkeypatch.setenv("OCT_WALL_DEADLINE", "1010.0")
    monkeypatch.setattr(costmodel, "predicted_wall", lambda g: 410.0)
    stage = "agg-packed:304b:scan"
    fresh_warmup.note_stage(stage, 123.0, via="xla-jit")
    assert costmodel.preflight(stage, now=1000.0) is True
    assert fresh_warmup.report()["refusals"] == []


def test_preflight_admits_when_fallback_is_no_cheaper(monkeypatch,
                                                      fresh_warmup):
    """A monolithic fallback that is predicted no cheaper than the
    refused program gains nothing: the gate must admit rather than
    trade one doomed compile for another (the xla-impl shape)."""
    monkeypatch.setenv("OCT_WALL_DEADLINE", "1090.0")
    monkeypatch.setattr(costmodel, "predicted_wall", lambda g: 410.0)
    assert costmodel.preflight(
        "agg-packed:304b:scan", now=1000.0,
        fallback_graph="verify_praos_core_bc",
    ) is True
    assert fresh_warmup.report()["refusals"] == []
    # a genuinely cheaper monolithic fallback -> refusal stands
    monkeypatch.setattr(
        costmodel, "predicted_wall",
        lambda g: 410.0 if g == "aggregate_core" else 40.0,
    )
    assert costmodel.preflight(
        "agg-packed:304b:scan", now=1000.0,
        fallback_graph="verify_praos_core_bc",
        action="xla-packed-fallback",
    ) is False
    assert fresh_warmup.report()["refusals"][0]["action"] == \
        "xla-packed-fallback"


def test_preflight_gate_kill_switch(monkeypatch, fresh_warmup):
    monkeypatch.setenv("OCT_WALL_DEADLINE", "1001.0")
    monkeypatch.setenv("OCT_COMPILE_GATE", "0")
    monkeypatch.setattr(costmodel, "predicted_wall", lambda g: 1e9)
    assert costmodel.preflight("agg-packed:304b:scan", now=1000.0) is True


# ---------------------------------------------------------------------------
# bench.py consumers
# ---------------------------------------------------------------------------


def test_bench_attempt2_estimate_prefers_measured_then_model():
    import bench

    # a banked measured estimate wins
    assert bench._attempt2_estimate(123.0, 600.0) == 123.0
    # no banked estimate: the octwall model-predicted cold wall (the
    # shipped costmodel.json pins the production window programs)
    pred = bench._predicted_cold_wall()
    assert pred is not None and pred > bench._COLD_WALL_OVERHEAD_S
    assert bench._attempt2_estimate(None, 600.0) == pred
    assert bench._attempt2_estimate(0.0, 600.0) == pred


def test_bench_attempt2_estimate_falls_back_without_model(monkeypatch):
    import bench

    monkeypatch.setattr(bench, "_predicted_cold_wall", lambda: None)
    assert bench._attempt2_estimate(None, 600.0) == 300.0


def test_bench_cold_wall_refuses_partial_pins(monkeypatch):
    """A missing pin must yield None, not a partial sum: 4s of
    unpack/reduce standing in for the ~750s aggregate wall would let
    attempt 2 launch into exactly the death the gate exists to skip."""
    import bench

    monkeypatch.setattr(
        costmodel, "predicted_wall",
        lambda g: None if g == "aggregate_core" else 2.0,
    )
    assert bench._predicted_cold_wall() is None


# ---------------------------------------------------------------------------
# The dispatch harness: a real window refused onto the fallback path
# ---------------------------------------------------------------------------


def _hash_tail(beta_decl_bt):
    from ouroboros_consensus_tpu.ops import blake2b

    bd = jnp.asarray(beta_decl_bt).astype(jnp.int32)
    b = bd.shape[0]
    tag_l = jnp.broadcast_to(jnp.asarray([ord("L")], jnp.int32), (b, 1))
    lv = blake2b.blake2b_fixed(
        jnp.concatenate([tag_l, bd], axis=-1), 65, 32)
    tag_n = jnp.broadcast_to(jnp.asarray([ord("N")], jnp.int32), (b, 1))
    eta1 = blake2b.blake2b_fixed(
        jnp.concatenate([tag_n, bd], axis=-1), 65, 32)
    eta = blake2b.blake2b_fixed(eta1, 32, 32)
    return eta, lv


def test_dispatch_refusal_rides_the_fallback_path(monkeypatch,
                                                  fresh_warmup):
    """End-to-end harness (acceptance): a qualifying packed bc window
    whose aggregate program is COLD and predicted over the remaining
    wall budget is refused pre-flight — dispatch_batch rides the
    per-lane packed path instead, the aggregate jit is NEVER built, and
    the refusal is recorded in the warmup report."""
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.protocol import praos
    from tests.test_aggregate import _stub_verdicts, make_params, real_chain
    from ouroboros_consensus_tpu.testing import fixtures

    pools = [fixtures.make_pool(50 + i, kes_depth=3) for i in range(2)]
    lview = fixtures.make_ledger_view(pools)
    params = make_params()
    nonce, hvs = real_chain(params, pools, lview, 8)
    assert len(hvs[0].vrf_proof) == 128  # batch-compatible window

    monkeypatch.delenv("OCT_VRF_AGG", raising=False)
    # 40 s of wall left, 500 s predicted for the aggregate, 50 s for
    # the per-lane xla twin (the fallback this CPU dispatch takes):
    # must refuse — the fallback is predicted 10x cheaper
    monkeypatch.setenv("OCT_WALL_DEADLINE", str(time.time() + 40.0))
    monkeypatch.setattr(
        costmodel, "predicted_wall",
        lambda g: 500.0 if g == "aggregate_core" else 50.0,
    )
    # the per-lane fallback would compile real crypto: stub the verify
    # (PR-2 pattern — the dispatch plumbing is what is under test)
    monkeypatch.setattr(pbatch, "verify_praos_any",
                        lambda *cols: _stub_verdicts(cols))
    agg_calls = []
    monkeypatch.setattr(
        pbatch, "_jitted_packed_agg",
        lambda layout, scan, mode="all": agg_calls.append(1)
        or pytest.fail("refused aggregate program was still dispatched"),
    )
    before = set(pbatch._JIT)
    try:
        pre, disp, b, carry = pbatch.dispatch_batch(
            params, lview, nonce, hvs
        )
        assert b == len(hvs)
        assert disp.impl != "agg"
        assert agg_calls == []
        refs = fresh_warmup.report()["refusals"]
        assert len(refs) == 1
        assert refs[0]["stage"].startswith("agg-packed:")
        # on the xla impl the recorded action is the per-lane packed
        # monolith, not the pk stage split
        assert refs[0]["action"] == "xla-packed-fallback"
        # and with wall to spare the SAME window takes the agg path
        monkeypatch.setenv("OCT_WALL_DEADLINE",
                           str(time.time() + 10_000.0))
        taken = []
        monkeypatch.setattr(
            pbatch, "_jitted_packed_agg",
            lambda layout, scan, mode="all": lambda *a: taken.append(1) or (
                ((np.zeros((5, (len(hvs) + 7) // 8 * 8), np.int64),)
                 + tuple(np.zeros(1) for _ in range(6))),
                np.zeros((5, 8)), np.zeros((32, 8)), np.zeros((32, 8)),
            ),
        )
        pre2, disp2, b2, _ = pbatch.dispatch_batch(
            params, lview, nonce, hvs
        )
        assert taken == [1]
        assert disp2.impl == "agg"
        assert len(fresh_warmup.report()["refusals"]) == 1  # no new one
    finally:
        for k in set(pbatch._JIT) - before:
            del pbatch._JIT[k]
