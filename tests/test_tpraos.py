"""TPraos: overlay schedule, host/device/native differential validation,
and the TPraos→Praos state translation (reference: Protocol/TPraos.hs,
Protocol/Praos/Translate.hs)."""

import math
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.protocol import praos, tpraos
from ouroboros_consensus_tpu.protocol.views import hash_key, hash_vrf_vk
from ouroboros_consensus_tpu.testing import fixtures

KES_DEPTH = 3


def mk_params(d, f=Fraction(1), epoch_length=500):
    inner = praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=5,
        active_slot_coeff=f,
        epoch_length=epoch_length,
        kes_depth=KES_DEPTH,
    )
    return tpraos.TPraosParams(praos=inner, decentralization=d)


def mk_setup(d, f=Fraction(1), n_delegs=2):
    params = mk_params(d, f)
    pool = fixtures.make_pool(0, kes_depth=KES_DEPTH)
    delegs = [
        fixtures.make_pool(10 + i, kes_depth=KES_DEPTH) for i in range(n_delegs)
    ]
    base = fixtures.make_ledger_view([pool])
    lview = tpraos.TPraosLedgerView(
        pool_distr=base.pool_distr,
        gen_delegs=[
            tpraos.GenDeleg(dp.vk_cold, hash_vrf_vk(dp.vrf_vk))
            for dp in delegs
        ],
    )
    return params, pool, delegs, lview


def test_overlay_schedule_density_and_assignment():
    params = mk_params(Fraction(1, 4), f=Fraction(1, 2))
    n = params.praos.epoch_length
    overlay = [s for s in range(n) if tpraos.overlay_position(params, s) is not None]
    # ceil-step schedule: exactly ceil(n*d) overlay slots in the epoch
    assert len(overlay) == math.ceil(n * Fraction(1, 4))
    # positions are consecutive integers
    pos = [tpraos.overlay_position(params, s) for s in overlay]
    assert pos == list(range(len(overlay)))
    # f=1/2 -> every second overlay position active, round-robin delegates
    seen = []
    for s in overlay:
        a = tpraos.overlay_slot_assignment(params, 2, s)
        assert a is not None
        active, j = a
        if active:
            seen.append(j)
    assert seen[:4] == [0, 1, 0, 1]
    # d=0: no overlay slots at all
    p0 = mk_params(Fraction(0))
    assert tpraos.overlay_position(p0, 17) is None


def forge_chain(params, pool, delegs, lview, n_slots):
    """Forge the deterministic TPraos chain: scheduled delegate on active
    overlay slots, the pool elsewhere (f=1 so it always wins)."""
    nonce = b"\x09" * 32
    hvs = []
    prev = None
    counters = {}
    for slot in range(1, n_slots):
        a = tpraos.overlay_slot_assignment(params, len(delegs), slot)
        if a is None:
            creds = pool
        else:
            active, j = a
            if not active:
                continue
            creds = delegs[j]
        c = counters.setdefault(creds.pool_id, 0)
        hv = fixtures.forge_header_view(
            params.praos, creds, slot=slot, epoch_nonce=nonce,
            prev_hash=prev, body_bytes=b"body-%d" % slot,
        )
        hvs.append(hv)
        prev = b"%032d" % slot
    return nonce, hvs


@pytest.fixture(scope="module")
def chain():
    params, pool, delegs, lview = mk_setup(Fraction(1, 3), f=Fraction(1))
    nonce, hvs = forge_chain(params, pool, delegs, lview, 120)
    return params, pool, delegs, lview, nonce, hvs


def _host_fold(params, lview, nonce, hvs):
    import dataclasses

    st = dataclasses.replace(tpraos.TPraosState(), epoch_nonce=nonce)
    for hv in hvs:
        t = tpraos.tick(params, lview, hv.slot, st)
        t = tpraos.TickedTPraosState(
            dataclasses.replace(t.state, epoch_nonce=nonce), t.ledger_view
        )
        st = tpraos.update(params, hv, hv.slot, t)
    return st


def _batch_validate(params, lview, nonce, hvs, backend):
    import dataclasses

    proto = tpraos.TPraosProtocol(params, use_device_batch=True)
    st = dataclasses.replace(tpraos.TPraosState(), epoch_nonce=nonce)
    ticked = tpraos.tick(params, lview, hvs[0].slot, st)
    ticked = tpraos.TickedTPraosState(
        dataclasses.replace(ticked.state, epoch_nonce=nonce), ticked.ledger_view
    )
    return proto.validate_batch(ticked, hvs, backend=backend)


@pytest.mark.slow
def test_host_device_native_agree(chain):
    params, pool, delegs, lview, nonce, hvs = chain
    assert len(hvs) > 30
    host_st = _host_fold(params, lview, nonce, hvs)
    for backend in ("device", "native"):
        res = _batch_validate(params, lview, nonce, hvs, backend)
        assert res.error is None, f"{backend}: {res.error!r}"
        assert res.n_valid == len(hvs)
        assert res.state == host_st, backend


@pytest.mark.slow
def test_wrong_delegate_rejected(chain):
    params, pool, delegs, lview, nonce, hvs = chain
    # find an overlay header and re-forge it with the OTHER delegate
    for idx, hv in enumerate(hvs):
        a = tpraos.overlay_slot_assignment(params, len(delegs), hv.slot)
        if a is not None and a[0]:
            j = a[1]
            other = delegs[1 - j]
            bad = fixtures.forge_header_view(
                params.praos, other, slot=hv.slot, epoch_nonce=nonce,
                prev_hash=hv.prev_hash, body_bytes=b"evil",
            )
            bad_hvs = list(hvs[: idx]) + [bad]
            break
    else:
        pytest.fail("no active overlay header in chain")
    for backend in ("device", "native", None):
        if backend is None:
            import dataclasses

            st = dataclasses.replace(tpraos.TPraosState(), epoch_nonce=nonce)
            err = None
            for hv in bad_hvs:
                t = tpraos.tick(params, lview, hv.slot, st)
                t = tpraos.TickedTPraosState(
                    dataclasses.replace(t.state, epoch_nonce=nonce),
                    t.ledger_view,
                )
                try:
                    st = tpraos.update(params, hv, hv.slot, t)
                except praos.PraosValidationError as e:
                    err = e
                    break
            assert isinstance(err, tpraos.WrongGenesisDelegate)
        else:
            res = _batch_validate(params, lview, nonce, bad_hvs, backend)
            assert res.n_valid == idx, backend
            assert isinstance(res.error, tpraos.WrongGenesisDelegate), backend


def test_inactive_overlay_slot_rejected():
    params, pool, delegs, lview = mk_setup(Fraction(1, 2), f=Fraction(1, 2))
    nonce = b"\x09" * 32
    # find an inactive overlay slot and forge a (pool) block there
    slot = next(
        s for s in range(1, 200)
        if tpraos.overlay_slot_assignment(params, 2, s) == (False, None)
    )
    hv = fixtures.forge_header_view(
        params.praos, pool, slot=slot, epoch_nonce=nonce,
        prev_hash=None, body_bytes=b"x",
    )
    res = _batch_validate(params, lview, nonce, [hv], "native")
    assert isinstance(res.error, tpraos.NonActiveSlot)


def test_translate_state_carries_nonces(chain):
    params, pool, delegs, lview, nonce, hvs = chain
    st = _host_fold(params, lview, nonce, hvs)
    p = tpraos.translate_state(st)
    assert isinstance(p, praos.PraosState) and not isinstance(p, tpraos.TPraosState)
    assert p.evolving_nonce == st.evolving_nonce
    assert p.candidate_nonce == st.candidate_nonce
    assert p.ocert_counters == st.ocert_counters
    assert p.last_slot == st.last_slot


def test_check_is_leader_overlay():
    params, pool, delegs, lview = mk_setup(Fraction(1, 2), f=Fraction(1))
    import dataclasses

    st = dataclasses.replace(tpraos.TPraosState(), epoch_nonce=b"\x07" * 32)
    ticked = tpraos.TickedTPraosState(st, lview)
    slot = next(
        s for s in range(1, 100)
        if (a := tpraos.overlay_slot_assignment(params, 2, s)) and a[0]
    )
    _active, j = tpraos.overlay_slot_assignment(params, 2, slot)
    cbl = fixtures.can_be_leader(delegs[j])
    assert tpraos.check_is_leader(params, cbl, slot, ticked, deleg_index=j)
    assert tpraos.check_is_leader(params, cbl, slot, ticked, deleg_index=1 - j) is None
