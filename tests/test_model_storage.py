"""Model-based (stateful) storage tests over the mock filesystem.

Mirror of the reference's strongest correctness tool — the
quickcheck-state-machine suites run against pure models with fault
injection (SURVEY §4 tier 2):

  * `test/storage-test/Test/Ouroboros/Storage/ImmutableDB/StateMachine.hs`
    (1,278 LoC; model `Model.hs`): random appends / reopens / corruption,
    expecting truncate-the-corrupted-tail recovery.
  * `.../VolatileDB/StateMachine.hs` (857): random puts (incl. dups),
    GC by slot with file granularity, reopen-reparses.
  * `.../ChainDB/StateMachine.hs` (1,710; model `ChainDB/Model.hs`,
    1,118): addBlock in arbitrary orders vs a pure chain-selection model,
    plus wipe/corrupt and reopen.

Here: hypothesis `RuleBasedStateMachine`s drive the REAL implementations
on an in-memory `MockFS` (utils/fs.py — the fs-sim analog) and compare
them against small pure models after every command.  Crashes use
MockFS.crash() — unsynced suffixes vanish (the torn-write model), and
the property is prefix-recovery, exactly the reference's crash spec.

Crypto runs through the native C++ verifier (protocol/praos.py
NativeVerifier) so hundreds of sequential validations stay cheap.
"""

from __future__ import annotations

import dataclasses
from fractions import Fraction

import pytest

pytest.importorskip(
    "hypothesis",
    reason="model-based storage tests need hypothesis; absent in this "
    "environment the suite must still collect (tier-1 runs with "
    "--continue-on-collection-errors, but a skip keeps the log clean)",
)
from hypothesis import HealthCheck, settings
from hypothesis import strategies as st
from hypothesis.stateful import (
    RuleBasedStateMachine,
    initialize,
    invariant,
    precondition,
    rule,
)

from ouroboros_consensus_tpu.block import forge_block
from ouroboros_consensus_tpu.ledger import ExtLedger
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.immutable import ImmutableDB
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.storage.volatile import VolatileDB
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils.fs import MockFS

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1),  # every pool leads every slot
    epoch_length=10_000,
    kes_depth=2,
)
POOLS = [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth) for i in range(2)]
LVIEW = fixtures.make_ledger_view(POOLS)
ETA0 = b"\x22" * 32
K = 4
CHUNK = 4
CRYPTO = praos.native_verifier_or_host()


def _forge(slot, block_no, prev, i=0):
    return forge_block(
        PARAMS, POOLS[i % 2], slot=slot, block_no=block_no,
        prev_hash=prev, epoch_nonce=ETA0,
    )


def _build_tree():
    """A fixed block tree, forged once: a 10-block main chain (even
    slots) with 2-block fork branches off heights 2, 5 and 8 (odd
    slots) — enough shape for chain selection to switch forks, hit the
    immutability window, and reject older-than-k blocks."""
    main = []
    prev = None
    for i in range(10):
        b = _forge(2 * i + 2, i, prev, i)
        main.append(b)
        prev = b.hash_
    branches = []
    for h in (2, 5, 8):
        parent = main[h]
        b1 = _forge(parent.slot + 1, h + 1, parent.hash_, h + 1)
        b2 = _forge(b1.slot + 2, h + 2, b1.hash_, h)
        branches.extend([b1, b2])
    return main, branches


_TREE = None


def tree():
    global _TREE
    if _TREE is None:
        _TREE = _build_tree()
    return _TREE


MACHINE_SETTINGS = settings(
    max_examples=12,
    stateful_step_count=25,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow, HealthCheck.data_too_large],
)


# ---------------------------------------------------------------------------
# ImmutableDB vs a list model (ImmutableDB/StateMachine.hs)
# ---------------------------------------------------------------------------


class ImmutableMachine(RuleBasedStateMachine):
    PATH = "imm"

    @initialize()
    def setup(self):
        self.fs = MockFS()
        self.blocks = tree()[0]
        self.db = ImmutableDB(self.PATH, chunk_size=CHUNK, fs=self.fs)
        self.model: list = []  # appended blocks, in order
        self.appended = 0

    # -- helpers ------------------------------------------------------------

    def _actual(self):
        return [(e.slot, e.hash_, raw) for e, raw in self.db.stream_all()]

    def _expected(self):
        return [(b.slot, b.hash_, b.bytes_) for b in self.model]

    def _chunk_layout(self):
        """(chunk_file, offset, size) per model block, recomputed the way
        appends laid them out."""
        out = []
        sizes: dict[int, int] = {}
        for b in self.model:
            n = b.slot // CHUNK
            off = sizes.get(n, 0)
            out.append((f"{self.PATH}/{n:05d}.chunk", off, len(b.bytes_)))
            sizes[n] = off + len(b.bytes_)
        return out

    # -- commands -----------------------------------------------------------

    @rule()
    def append(self):
        if self.appended >= len(self.blocks):
            return
        b = self.blocks[self.appended]
        self.db.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
        self.model.append(b)
        self.appended += 1

    @rule()
    def reopen(self):
        self.db = ImmutableDB(
            self.PATH, chunk_size=CHUNK, validate_all=True, fs=self.fs
        )
        assert self._actual() == self._expected()

    @rule(keep=st.floats(0.0, 1.0))
    def crash_and_reopen(self, keep):
        """Torn-write crash: recovery must yield a PREFIX of the model
        (nothing reordered, nothing invented), then resync the model."""
        self.fs.crash(keep)
        self.db = ImmutableDB(
            self.PATH, chunk_size=CHUNK, validate_all=True, fs=self.fs
        )
        actual = self._actual()
        assert actual == self._expected()[: len(actual)], "not a prefix"
        self.model = self.model[: len(actual)]
        self.appended = len(self.model)

    @rule(data=st.data())
    def corrupt_block_and_reopen(self, data):
        """Flip one byte inside a stored block: reopen-with-validation
        must truncate from that block on (CRC mismatch ⇒ corrupted-tail
        truncation, Impl/Validation.hs:67)."""
        if not self.model:
            return
        i = data.draw(st.integers(0, len(self.model) - 1))
        path, off, size = self._chunk_layout()[i]
        at = data.draw(st.integers(0, size - 1))
        self.fs.corrupt_byte(path, off + at)
        self.db = ImmutableDB(
            self.PATH, chunk_size=CHUNK, validate_all=True, fs=self.fs
        )
        self.model = self.model[:i]
        self.appended = len(self.model)
        assert self._actual() == self._expected()

    @rule(data=st.data())
    def truncate_index_and_reopen(self, data):
        """Index damage alone loses NO blocks: the chunk reparse rebuilds
        it (crash-before-index-flush recovery)."""
        if not self.model:
            return
        b = self.model[-1]
        ipath = f"{self.PATH}/{b.slot // CHUNK:05d}.index"
        if not self.fs.exists(ipath):
            return
        size = self.fs.getsize(ipath)
        self.fs.truncate_file(ipath, data.draw(st.integers(0, max(0, size - 1))))
        self.db = ImmutableDB(
            self.PATH, chunk_size=CHUNK, validate_all=True, fs=self.fs
        )
        assert self._actual() == self._expected()

    @rule(data=st.data())
    def truncate_after(self, data):
        if not self.model:
            return
        i = data.draw(st.integers(0, len(self.model) - 1))
        from ouroboros_consensus_tpu.block.abstract import Point

        self.db.truncate_after(Point(self.model[i].slot, self.model[i].hash_))
        self.model = self.model[: i + 1]
        self.appended = len(self.model)
        assert self._actual() == self._expected()

    # -- invariants ---------------------------------------------------------

    @invariant()
    def tip_matches(self):
        if not hasattr(self, "db"):
            return
        t = self.db.tip()
        if not self.model:
            assert t is None
        else:
            assert t is not None
            assert (t.slot, t.hash_) == (self.model[-1].slot, self.model[-1].hash_)

    @invariant()
    def reads_match(self):
        if not hasattr(self, "db") or not self.model:
            return
        from ouroboros_consensus_tpu.block.abstract import Point

        b = self.model[-1]
        assert self.db.get_block_bytes(Point(b.slot, b.hash_)) == b.bytes_


TestImmutableModel = ImmutableMachine.TestCase
TestImmutableModel.settings = MACHINE_SETTINGS


# ---------------------------------------------------------------------------
# VolatileDB vs a file-aware model (VolatileDB/StateMachine.hs)
# ---------------------------------------------------------------------------

MAX_PER_FILE = 3


class VolatileModel:
    """Pure model of the VolatileDB including its file granularity —
    which is API-visible through garbageCollect (whole files only)."""

    def __init__(self):
        self.files: dict[int, list] = {}  # file_no -> blocks in put order
        self.by_hash: dict[bytes, object] = {}
        self.write_file = 0

    def put(self, blk):
        if blk.hash_ in self.by_hash:
            return
        if len(self.files.get(self.write_file, [])) >= MAX_PER_FILE:
            self.write_file += 1
        self.files.setdefault(self.write_file, []).append(blk)
        self.by_hash[blk.hash_] = blk

    def gc(self, slot):
        for n in list(self.files):
            if n == self.write_file:
                continue
            if all(b.slot < slot for b in self.files[n]):
                for b in self.files.pop(n):
                    del self.by_hash[b.hash_]

    def successors(self, prev):
        return {b.hash_ for b in self.by_hash.values() if b.prev_hash == prev}


class VolatileMachine(RuleBasedStateMachine):
    PATH = "vol"

    @initialize()
    def setup(self):
        self.fs = MockFS()
        main, branches = tree()
        self.pool = main + branches
        self.db = VolatileDB(self.PATH, max_blocks_per_file=MAX_PER_FILE, fs=self.fs)
        self.model = VolatileModel()

    @rule(data=st.data())
    def put(self, data):
        b = data.draw(st.sampled_from(self.pool))
        self.db.put_block(b)
        self.model.put(b)

    @rule(data=st.data())
    def get(self, data):
        b = data.draw(st.sampled_from(self.pool))
        raw = self.db.get_block_bytes(b.hash_)
        if b.hash_ in self.model.by_hash:
            assert raw == b.bytes_
        else:
            assert raw is None

    @rule(data=st.data())
    def successors(self, data):
        b = data.draw(st.sampled_from(self.pool))
        for prev in (b.prev_hash, b.hash_):
            assert self.db.filter_by_predecessor(prev) == self.model.successors(prev)

    @rule(slot=st.integers(0, 30))
    def gc(self, slot):
        self.db.garbage_collect(slot)
        self.model.gc(slot)
        assert set(self.db.all_hashes()) == set(self.model.by_hash)

    @rule()
    def reopen(self):
        self.db = VolatileDB(self.PATH, max_blocks_per_file=MAX_PER_FILE, fs=self.fs)
        assert set(self.db.all_hashes()) == set(self.model.by_hash)

    @rule(keep=st.floats(0.0, 1.0))
    def crash_and_reopen(self, keep):
        """After a crash each surviving file is a torn-truncated prefix;
        reopen reparses what remains. Check per-file prefix, resync."""
        self.fs.crash(keep)
        self.db = VolatileDB(self.PATH, max_blocks_per_file=MAX_PER_FILE, fs=self.fs)
        survived = set(self.db.all_hashes())
        assert survived <= set(self.model.by_hash)
        # surviving blocks read back intact
        for h in survived:
            blk = self.model.by_hash[h]
            assert self.db.get_block_bytes(h) == blk.bytes_
        # resync the model. The write file is the HIGHEST-numbered file on
        # disk, not the highest with surviving blocks: a tail file torn to
        # zero records still exists, is the write file, and no longer
        # shields earlier files from GC (reopen semantics, volatile.py
        # _reopen; found by this machine).
        new = VolatileModel()
        for n in sorted(self.model.files):
            kept = [b for b in self.model.files[n] if b.hash_ in survived]
            if kept:
                new.files[n] = kept
                for b in kept:
                    new.by_hash[b.hash_] = b
        ns = [
            int(f[len("blocks-"):-len(".dat")])
            for f in self.fs.listdir(self.PATH)
            if f.startswith("blocks-") and f.endswith(".dat")
        ]
        new.write_file = max(ns) if ns else 0
        self.model = new

    @invariant()
    def member_consistent(self):
        if not hasattr(self, "db"):
            return
        assert set(self.db.all_hashes()) == set(self.model.by_hash)


TestVolatileModel = VolatileMachine.TestCase
TestVolatileModel.settings = MACHINE_SETTINGS


# ---------------------------------------------------------------------------
# ChainDB vs a pure chain-selection model (ChainDB/StateMachine.hs, Model.hs)
# ---------------------------------------------------------------------------


class ChainModel:
    """Pure model of ChainDB semantics: volatile block graph + the
    chain-selection rule (adopt the best candidate through the new block
    iff strictly preferred), the k-deep immutability window, olderThanK
    rejection, and file-granular volatile GC after copy."""

    def __init__(self, protocol, k):
        self.protocol = protocol
        self.k = k
        self.vol = VolatileModel()
        self.immutable: list = []
        self.current: list = []

    def chain(self):
        return self.immutable + self.current

    def _anchor_hash(self):
        return self.immutable[-1].hash_ if self.immutable else None

    def _candidates_through(self, via_hash):
        """Paths from the anchor through `via_hash` in the volatile graph
        (isReachable + extendWithSuccessors)."""
        back = []
        h = via_hash
        root = self._anchor_hash()
        while True:
            blk = self.vol.by_hash.get(h)
            if blk is None:
                return []
            back.append(blk)
            if blk.prev_hash == root:
                break
            h = blk.prev_hash
            if h is None:
                return []
        prefix = list(reversed(back))
        out = []
        stack = [prefix]
        while stack:
            path = stack.pop()
            succs = self.vol.successors(path[-1].hash_)
            if not succs:
                out.append(path)
                continue
            for s in succs:
                stack.append(path + [self.vol.by_hash[s]])
        return out

    def add(self, blk):
        if self.immutable and blk.slot <= self.immutable[-1].slot:
            return  # olderThanK
        self.vol.put(blk)
        cands = self._candidates_through(blk.hash_)
        if not cands:
            return
        sv = self.protocol.select_view
        cur = sv(self.current[-1].header) if self.current else None
        best = None
        best_v = cur
        for c in cands:
            v = sv(c[-1].header)
            if self.protocol.compare_candidates(best_v, v) > 0:
                best, best_v = c, v
        if best is None:
            return
        self.current = best
        # copy-to-immutable + GC (file granularity)
        excess = len(self.current) - self.k
        if excess > 0:
            moved, self.current = self.current[:excess], self.current[excess:]
            self.immutable.extend(moved)
            self.vol.gc(moved[-1].slot + 1)


def _mk_ext():
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(LVIEW, PARAMS.stability_window)
    )
    protocol = PraosProtocol(PARAMS, use_device_batch=False, crypto=CRYPTO)
    return ExtLedger(ledger, protocol)


def _genesis(ext):
    st_ = ext.genesis(ext.ledger.genesis_state([]))
    return dataclasses.replace(
        st_,
        header_state=dataclasses.replace(
            st_.header_state,
            chain_dep_state=dataclasses.replace(
                st_.header_state.chain_dep_state, epoch_nonce=ETA0
            ),
        ),
    )


class ChainDBMachine(RuleBasedStateMachine):
    PATH = "chain"

    @initialize()
    def setup(self):
        self.fs = MockFS()
        main, branches = tree()
        self.pool = main + branches
        self.ext = _mk_ext()
        self.db = open_chaindb(
            self.PATH, self.ext, _genesis(self.ext), K, fs=self.fs
        )
        # the real VolatileDB uses max_blocks_per_file=1000: mirror that
        # (file granularity never triggers in a 16-block tree)
        self.model = ChainModel(self.ext.protocol, K)
        self.model_vol_max = 1000
        self.all_blocks = {b.hash_: b for b in self.pool}
        self.bad_hashes: set[bytes] = set()

    def _assert_same_chain(self):
        actual = [b.hash_ for b in self.db.stream_all()]
        expected = [b.hash_ for b in self.model.chain()]
        assert actual == expected, (
            f"chain mismatch: impl {len(actual)} blocks, model {len(expected)}"
        )

    @rule(data=st.data())
    def add_block(self, data):
        b = data.draw(st.sampled_from(self.pool))
        self.db.add_block(b)
        self.model.add(b)
        self._assert_same_chain()

    @rule(data=st.data())
    def add_invalid_block(self, data):
        """A block with a corrupted KES signature extending any tree
        block: chain selection must reject it, mark it invalid, and
        NEVER adopt a chain through it (the model ignores it)."""
        from ouroboros_consensus_tpu.block.praos_block import Block, Header

        parent = data.draw(st.sampled_from(self.pool))
        good = _forge(parent.slot + 1, parent.block_no + 1, parent.hash_)
        bad_sig = bytes([good.header.kes_sig[0] ^ 0xFF]) + good.header.kes_sig[1:]
        bad = Block(Header(good.header.body, bad_sig), good.txs)
        self.all_blocks[bad.hash_] = bad
        self.bad_hashes.add(bad.hash_)
        self.db.add_block(bad)
        # model unchanged — and the impl must agree
        self._assert_same_chain()
        anchor = self.model.immutable[-1] if self.model.immutable else None
        if anchor is None or bad.slot > anchor.slot:
            # validation only happens for candidates PREFERRED over the
            # current chain (ChainSel.hs:874 sorts then validates): a
            # corrupted block on a LOSING fork is stored, stays
            # unvalidated and therefore unmarked — only a preferred
            # candidate must end up marked invalid (olderThanK blocks
            # are dropped before validation and stay unmarked too)
            sv = self.model.protocol.select_view
            cur_v = (
                sv(self.model.current[-1].header)
                if self.model.current else None
            )
            preferred = (
                self.model.protocol.compare_candidates(cur_v, sv(bad.header))
                > 0
            )
            if preferred:
                assert (
                    self.db.get_is_invalid_block(bad.hash_) is not None
                    or bad.hash_ not in self.db.volatile.all_hashes()
                    or not self._connected(bad)
                )

    def _connected(self, blk):
        """Is blk's parent reachable (disconnected blocks sit unvalidated
        in the volatile store until their parent arrives)?"""
        h = blk.prev_hash
        anchor = self.model.immutable[-1].hash_ if self.model.immutable else None
        while h is not None:
            if h == anchor:
                return True
            info = self.db.volatile.get_block_info(h)
            if info is None:
                return False
            h = info.prev_hash
        return anchor is None

    @rule(validate_all=st.booleans())
    def reopen(self, validate_all):
        """Close (snapshot) and reopen: selection must be rebuilt
        identically from disk state."""
        self.db.close()
        self.db = open_chaindb(
            self.PATH, self.ext, _genesis(self.ext), K,
            validate_all=validate_all, fs=self.fs,
        )
        self._assert_same_chain()

    @rule(keep=st.floats(0.0, 1.0))
    def crash_and_reopen(self, keep):
        """Torn-write crash (no clean shutdown): reopen WITH full
        revalidation must recover a consistent state — the immutable
        part is a PREFIX of the model's immutable chain, the selected
        chain revalidates, and the model resyncs to the survivors (the
        q-s-m wipe/corrupt recovery property)."""
        self.fs.crash(keep)
        self.db = open_chaindb(
            self.PATH, self.ext, _genesis(self.ext), K,
            validate_all=True, fs=self.fs,
        )
        actual = [b.hash_ for b in self.db.stream_all()]
        model_imm = [b.hash_ for b in self.model.immutable]
        # immutable prefix survived (fsynced up to the snapshot/flush
        # watermark; never reordered or invented)
        n_imm = self.db.immutable.n_blocks()
        assert actual[:n_imm] == model_imm[:n_imm]
        # resync the model from the SURVIVING INPUTS only (immutable
        # prefix + surviving VALID volatile blocks) and let the model
        # run its OWN chain selection over them — an independent check
        # that recovery picked the best reachable chain, not merely an
        # internally-consistent one
        by_hash = self.all_blocks
        new = ChainModel(self.ext.protocol, K)
        new.immutable = [by_hash[h] for h in actual[:n_imm]]
        survivors = [
            by_hash[h]
            for h in self.db.volatile.all_hashes()
            if h not in self.bad_hashes
        ]
        for b in sorted(survivors, key=lambda b: (b.slot, b.block_no)):
            new.add(b)
        self.model = new
        self._assert_same_chain()

    @invariant()
    def tip_consistent(self):
        if not hasattr(self, "db"):
            return
        tp = self.db.tip_point()
        chain = self.model.chain()
        if chain:
            assert tp is not None and tp.hash_ == chain[-1].hash_
        else:
            assert tp is None


TestChainDBModel = ChainDBMachine.TestCase
TestChainDBModel.settings = MACHINE_SETTINGS


# ---------------------------------------------------------------------------
# LedgerDB snapshots vs a model (LedgerDB/OnDisk.hs, 1,197 LoC)
# ---------------------------------------------------------------------------


class LedgerDBMachine(RuleBasedStateMachine):
    """Push/prune/rollback in memory; snapshot/corrupt/restore on the
    mock FS — the q-s-m OnDisk suite's command set. The model is the
    plain list of (point, state) the AnchoredSeq must equal, plus the
    slot of the newest UNCORRUPTED snapshot for restore checks."""

    SNAP_DIR = "ldb-snaps"
    K = 3

    @initialize()
    def setup(self):
        from ouroboros_consensus_tpu.storage.ledgerdb import LedgerDB

        self.fs = MockFS()
        self.ext = _mk_ext()
        self.genesis = _genesis(self.ext)
        self.db = LedgerDB(self.ext, self.K, self.genesis, fs=self.fs)
        self.blocks = tree()[0]  # the 10-block main chain
        self.n_pushed = 0
        # model: full chain of states from genesis; the anchor index only
        # moves FORWARD (pruning discards history — rollback cannot pass
        # it, exactly the k-rollback bound)
        self.model_states = [self.genesis]
        self.anchor_idx = 0
        self.good_snapshots: set[int] = set()

    def _window(self):
        return self.model_states[self.anchor_idx:]

    @rule()
    def push(self):
        if self.n_pushed >= len(self.blocks):
            return
        b = self.blocks[self.n_pushed]
        st = self.db.push(b)
        self.model_states.append(st)
        self.anchor_idx = max(self.anchor_idx, len(self.model_states) - 1 - self.K)
        self.n_pushed += 1

    @rule(data=st.data())
    def rollback(self, data):
        n = data.draw(st.integers(0, self.K + 1))
        ok = self.db.rollback(n)
        # rollback must refuse past the ANCHOR (pruned history is gone)
        assert ok == (n <= len(self._window()) - 1)
        if ok and n:
            del self.model_states[-n:]
            self.n_pushed -= n

    @rule()
    def snapshot(self):
        name = self.db.take_snapshot(self.SNAP_DIR, keep=2)
        anchor = self._window()[0]
        tip = anchor.header_state.tip
        slot = 0 if tip is None else tip.slot
        if name is not None:
            assert name == f"snapshot-{slot}"
            # only a WRITE makes the snapshot good — take_snapshot
            # returning None means the (possibly corrupted) file on
            # disk was left untouched
            self.good_snapshots.add(slot)
        # keep-2 pruning (DiskPolicy.hs:87)
        from ouroboros_consensus_tpu.storage.ledgerdb import LedgerDB

        on_disk = LedgerDB.list_snapshots(self.SNAP_DIR, fs=self.fs)
        assert len(on_disk) <= 2
        self.good_snapshots &= set(on_disk)

    @rule(data=st.data())
    def corrupt_snapshot(self, data):
        from ouroboros_consensus_tpu.storage.ledgerdb import LedgerDB

        snaps = LedgerDB.list_snapshots(self.SNAP_DIR, fs=self.fs)
        if not snaps:
            return
        slot = data.draw(st.sampled_from(snaps))
        path = f"{self.SNAP_DIR}/snapshot-{slot}"
        self.fs.corrupt_byte(path, data.draw(
            st.integers(0, self.fs.getsize(path) - 1)
        ))
        self.good_snapshots.discard(slot)

    @rule()
    def restore(self):
        """init_from_snapshots: newest USABLE snapshot (corrupt ones
        skipped and deleted), replayed to the immutable tip — here there
        is no ImmutableDB, so restore lands exactly on the snapshot."""
        from ouroboros_consensus_tpu.storage.ledgerdb import LedgerDB

        class _EmptyImm:
            def stream_from(self, *_a):
                return iter(())

            def stream_all(self):
                return iter(())

        db2 = LedgerDB.init_from_snapshots(
            self.ext, self.K, self.SNAP_DIR, self.genesis, _EmptyImm(),
            fs=self.fs,
        )
        tip = db2.current().header_state.tip
        got = 0 if tip is None else tip.slot
        expect = max(self.good_snapshots) if self.good_snapshots else 0
        assert got == expect, (got, expect)

    @invariant()
    def window_matches(self):
        if not hasattr(self, "db"):
            return
        win = self._window()
        assert self.db.volatile_length() == len(win) - 1
        assert self.db.current() == win[-1]
        assert self.db.anchor() == win[0]


TestLedgerDBModel = LedgerDBMachine.TestCase
TestLedgerDBModel.settings = MACHINE_SETTINGS
