"""The columnar-sidecar plane (PR 17), differentially: a sidecar-fed
replay must be verdict-, error-taxonomy- and nonce-carry-IDENTICAL to
the parse path on clean, corrupted, and mixed draft-03/batch-compatible
chains — the sidecar is a cache of the parse, never an authority.

The suite covers the probe's outcome vocabulary (hit/miss/stale/torn),
the writer-only backfill contract (a read-only open never writes), the
hot-path honesty invariant (the sidecar's body-hash columns equal the
exact host digests — a wrong column would silently arbitrate every
block onto the slow path without failing a verdict), resume across a
sidecared/un-sidecared chunk boundary, and the device-hash lever."""

from __future__ import annotations

import os
import shutil
from fractions import Fraction

import numpy as np
import pytest

from ouroboros_consensus_tpu import native_loader, obs
from ouroboros_consensus_tpu.obs import recovery
from ouroboros_consensus_tpu.obs.warmup import WARMUP
from ouroboros_consensus_tpu.ops import blake2b as b2
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.views import ViewColumns
from ouroboros_consensus_tpu.storage import sidecar as sc_mod
from ouroboros_consensus_tpu.storage.immutable import _chunk_name
from ouroboros_consensus_tpu.testing import chaos, fixtures
from ouroboros_consensus_tpu.tools import db_analyser as ana
from ouroboros_consensus_tpu.tools import db_synthesizer as synth

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=60,
    kes_depth=3,
)
POOL = fixtures.make_pool(11, kes_depth=3)
LVIEW = fixtures.make_ledger_view([POOL])
N_BLOCKS = 40


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    WARMUP.reset()
    obs.reset_for_tests()
    recovery.reset_for_tests()
    for var in ("OCT_CHAOS", "OCT_CHAOS_SEED", "OCT_CHECKPOINT",
                "OCT_RESUME", "OCT_SIDECAR", "OCT_SIDECAR_DEVICE_HASH",
                "OCT_COLUMNAR", "OCT_VRF_BATCH", "OCT_TRACE"):
        monkeypatch.delenv(var, raising=False)
    chaos.reset()
    sc_mod.reset_counters()
    yield
    WARMUP.reset()
    obs.reset_for_tests()
    recovery.reset_for_tests()
    chaos.reset()
    sc_mod.reset_counters()


def _need_native():
    if native_loader.load() is None:
        pytest.skip("native loader unavailable: the sidecar plane is "
                    "parse-path-only on this box")


def _forge(path, blocks=N_BLOCKS, resume=False):
    synth.synthesize(path, PARAMS, [POOL], LVIEW,
                     synth.ForgeLimit(blocks=blocks),
                     chunk_size=PARAMS.epoch_length, resume=resume)


@pytest.fixture(scope="module")
def pristine(tmp_path_factory):
    if native_loader.load() is None:
        pytest.skip("native loader unavailable")
    path = str(tmp_path_factory.mktemp("sidecar") / "pristine")
    shutil.rmtree(path, ignore_errors=True)
    _forge(path)
    return path


def _reval(path, **kw):
    kw.setdefault("backend", "host")
    kw.setdefault("validate_all", "stream")
    return ana.revalidate(path, PARAMS, LVIEW, **kw)


def _copy(pristine, tmp_path):
    db = str(tmp_path / "db")
    shutil.copytree(pristine, db)
    return db


def _chunk_and_sidecar(db, chunk=0):
    """(chunk bytes, entries, loaded SidecarColumns, outcome) through
    the same fs seam the replay uses."""
    imm = ana.open_immutable(db)
    n = imm._chunks[chunk]
    entries = imm._entries[n]
    data = imm.fs.read_bytes(os.path.join(imm.path, _chunk_name(n)))
    sc, outcome = sc_mod.load_sidecar(imm.fs, imm.path, n, data,
                                      len(entries))
    return data, entries, sc, outcome


def _prefix_states(db):
    """Pristine-prefix oracle: final PraosState at every prefix length
    (same construction as tests/test_repair.pristine_states)."""
    states = {0: praos.PraosState()}
    st = praos.PraosState()
    res = ana.ValidationResult()
    i = 0
    imm = ana.open_immutable(db)
    for hv in ana._stream_views(imm, res):
        ticked = praos.tick(PARAMS, LVIEW, hv.slot, st)
        st = praos.update(PARAMS, hv, hv.slot, ticked)
        i += 1
        states[i] = st
    return states


# ---------------------------------------------------------------------------
# format + probe units
# ---------------------------------------------------------------------------


def test_forge_writes_sealed_sidecars(pristine):
    """db_synthesizer back-fills every chunk's sidecar at forge time;
    a fresh probe is a HIT whose lane count matches the index."""
    imm = ana.open_immutable(pristine)
    assert len(imm._chunks) == 2  # 40 blocks over 60-slot chunks
    for chunk in range(len(imm._chunks)):
        assert os.path.exists(
            sc_mod.sidecar_path(imm.path, imm._chunks[chunk])
        )
        _, entries, sc, outcome = _chunk_and_sidecar(pristine, chunk)
        assert outcome == "hit" and sc is not None
        assert sc.n == len(entries)


def test_probe_outcome_classification(pristine, tmp_path):
    """The probe's whole vocabulary, one manipulation per word:
    structural truncation is `torn`, any seal mismatch is `stale`, an
    absent file is `miss` — and NONE of them is ever a crash."""
    db = _copy(pristine, tmp_path)
    imm = ana.open_immutable(db)
    n = imm._chunks[0]
    path = sc_mod.sidecar_path(imm.path, n)
    data = imm.fs.read_bytes(os.path.join(imm.path, _chunk_name(n)))
    n_entries = len(imm._entries[n])
    pristine_cols = open(path, "rb").read()

    def probe(chunk_bytes=data, count=n_entries):
        sc, outcome = sc_mod.load_sidecar(imm.fs, imm.path, n,
                                          chunk_bytes, count)
        return outcome

    assert probe() == "hit"
    # torn: truncated inside the header, then inside the payload
    for cut in (0, 10, sc_mod.HEADER_SIZE + 7):
        with open(path, "wb") as f:
            f.write(pristine_cols[:cut])
        assert probe() == "torn", cut
    # torn: wrong magic (a foreign or half-written file)
    with open(path, "wb") as f:
        f.write(b"XXXXXXXX" + pristine_cols[8:])
    assert probe() == "torn"
    # stale: one flipped payload byte breaks the payload CRC seal
    flip = bytearray(pristine_cols)
    flip[sc_mod.HEADER_SIZE + 3] ^= 0x40
    with open(path, "wb") as f:
        f.write(bytes(flip))
    assert probe() == "stale"
    # restore the real file: remaining words are seal mismatches
    with open(path, "wb") as f:
        f.write(pristine_cols)
    assert probe(count=n_entries + 1) == "stale"  # index drifted
    assert probe(chunk_bytes=data + b"x") == "stale"  # chunk grew
    assert probe(chunk_bytes=data[:-1]) == "stale"  # chunk shrank
    os.unlink(path)
    assert probe() == "miss"


def test_hot_path_honesty_digests_match_exactly(pristine):
    """The sidecar's body-hash columns equal the exact host digests on
    a clean chunk. This is the anti-silent-degradation guard: a wrong
    `header_end`/`body_hash` column would not flip any verdict (the
    per-block arbitration re-checks on host), it would just quietly
    route EVERY block through the slow path — so the fast path must be
    proven exact, not merely verdict-preserving."""
    for chunk in range(2):
        data, entries, sc, outcome = _chunk_and_sidecar(pristine, chunk)
        assert outcome == "hit"
        starts = np.asarray(sc.arrays["header_end"], np.int64)
        ends = np.asarray([e.offset + e.size for e in entries], np.int64)
        digests = b2.hash_spans(data, starts, ends)
        assert np.array_equal(digests, sc.arrays["body_hash"])
        # and the integrity hook agrees: the whole chunk is good
        hook = sc_mod.integrity_batch_hook(sc)
        assert hook(data, entries) == len(entries)


def test_pieces_equivalent_to_parse(pristine):
    """`SidecarColumns.pieces` reconstructs the SAME ViewColumns the
    native parse builds — field for field, byte for byte."""
    for chunk in range(2):
        data, entries, sc, outcome = _chunk_and_sidecar(pristine, chunk)
        assert outcome == "hit"
        offsets = np.asarray([e.offset for e in entries], np.int64)
        hc = native_loader.extract_headers(data, offsets)
        want = ViewColumns.pieces_from_header_columns(hc)
        got = sc.pieces(data)
        assert want is not None and got is not None
        assert len(got) == len(want)
        from dataclasses import fields
        for gp, wp in zip(got, want):
            for f in fields(ViewColumns):
                assert np.array_equal(
                    np.asarray(getattr(gp, f.name)),
                    np.asarray(getattr(wp, f.name)),
                ), (chunk, f.name)


# ---------------------------------------------------------------------------
# the differential headline: sidecar replay == parse replay
# ---------------------------------------------------------------------------


def test_clean_replay_differential_and_killswitch(pristine, monkeypatch):
    """OCT_SIDECAR=0 is the acceptance kill-switch: verdict, block
    counts and final state (nonce carry included) are identical with
    the plane on (every chunk a HIT) and off (counters untouched)."""
    sc_mod.reset_counters()
    on = _reval(pristine)
    assert on.error is None and on.n_valid == N_BLOCKS
    assert sc_mod.counters()["hit"] == 2

    monkeypatch.setenv("OCT_SIDECAR", "0")
    sc_mod.reset_counters()
    off = _reval(pristine)
    assert sc_mod.counters() == {k: 0 for k in sc_mod.SIDECAR_OUTCOMES}
    assert (off.n_blocks, off.n_valid, off.error) == \
        (on.n_blocks, on.n_valid, on.error)
    assert off.final_state == on.final_state


def test_backfill_is_writer_only(pristine, tmp_path):
    """An un-sidecared store: the read-only replay parses (miss) and
    leaves the disk byte-untouched; the first WRITER open pays the
    parse once and back-fills; the next replay hits. All three runs
    verdict-identical."""
    db = _copy(pristine, tmp_path)
    imm_dir = os.path.join(db, "immutable")
    for f in list(os.listdir(imm_dir)):
        if f.endswith(".cols"):
            os.unlink(os.path.join(imm_dir, f))
    listing = sorted(os.listdir(imm_dir))

    sc_mod.reset_counters()
    ro = _reval(db)  # read-only analysis
    assert ro.error is None and ro.n_valid == N_BLOCKS
    c = sc_mod.counters()
    assert c["miss"] == 2 and c["rebuilt"] == 0
    assert sorted(os.listdir(imm_dir)) == listing  # wrote NOTHING

    sc_mod.reset_counters()
    wr = _reval(db, repair=True)  # writer open: backfill allowed
    c = sc_mod.counters()
    assert c["miss"] == 2 and c["rebuilt"] == 2
    assert all(
        os.path.exists(os.path.join(imm_dir, f"{n:05d}.cols"))
        for n in (0, 1)
    )

    sc_mod.reset_counters()
    hot = _reval(db)
    assert sc_mod.counters()["hit"] == 2
    for r in (wr, hot):
        assert r.error is None and r.n_valid == ro.n_valid
        assert r.final_state == ro.final_state


def test_corrupted_chain_differential(pristine, tmp_path, monkeypatch):
    """A sidecar whose seal covers ROTTEN chunk bytes (rot landed
    before the rebuild, so every seal matches) must not launder them:
    the probe hits, the integrity sweep catches the rot, the anomaly
    path re-runs the exact host walk — and the truncation point, the
    replay verdict and the nonce carry equal both the kill-switch
    replay and the pristine prefix."""
    oracle = _prefix_states(pristine)
    db = _copy(pristine, tmp_path)
    imm_dir = os.path.join(db, "immutable")

    # corrupt one BODY byte of block 5 in chunk 0 (first byte past the
    # header: the sidecar's own header_end column says where that is)
    data, entries, sc, outcome = _chunk_and_sidecar(db, 0)
    assert outcome == "hit"
    rot_at = int(sc.arrays["header_end"][5])
    chunk_file = os.path.join(imm_dir, _chunk_name(0))
    blob = bytearray(open(chunk_file, "rb").read())
    blob[rot_at] ^= 0xA5
    with open(chunk_file, "wb") as f:
        f.write(bytes(blob))

    # rebuild chunk 0's sidecar OVER the rotten bytes — seals now match
    os.unlink(os.path.join(imm_dir, "00000.cols"))
    imm = ana.open_immutable(db)
    assert sc_mod.backfill_store(imm) == 1
    _, _, sc2, outcome2 = _chunk_and_sidecar(db, 0)
    assert outcome2 == "hit"  # the trap is armed: a hit over rot

    sc_mod.reset_counters()
    r_on = _reval(db)
    assert sc_mod.counters()["hit"] >= 1
    assert r_on.error is None and r_on.n_valid == 5
    assert r_on.final_state == oracle[5]
    assert r_on.repairs is None  # read-only: verdict-only truncation

    monkeypatch.setenv("OCT_SIDECAR", "0")
    r_off = _reval(db)
    assert (r_off.n_blocks, r_off.n_valid, r_off.error) == \
        (r_on.n_blocks, r_on.n_valid, r_on.error)
    assert r_off.final_state == r_on.final_state


def test_mixed_proof_format_store_differential(tmp_path, monkeypatch):
    """A store forged across an OCT_VRF_BATCH flip (20 batch-compatible
    128-byte proofs, then draft-03 80-byte ones) has ragged signed-body
    widths: the sidecar drops UNIFORM and serves the span-gather
    fallback, splitting pieces at the format boundary exactly like
    `pieces_from_header_columns` — and the replay still equals the
    kill-switch replay."""
    _need_native()
    db = str(tmp_path / "mixed")
    monkeypatch.setenv("OCT_VRF_BATCH", "1")
    _forge(db, blocks=20)
    monkeypatch.setenv("OCT_VRF_BATCH", "0")
    _forge(db, blocks=N_BLOCKS, resume=True)
    monkeypatch.delenv("OCT_VRF_BATCH")

    # the flip landed mid-store: both formats present
    imm = ana.open_immutable(db)
    lens = set()
    for chunk in range(len(imm._chunks)):
        data, entries, sc, outcome = _chunk_and_sidecar(db, chunk)
        assert outcome == "hit"
        lens |= set(np.asarray(sc.arrays["vrf_proof_len"]).tolist())
        pieces = sc.pieces(data)
        assert pieces is not None
        if not sc.uniform:
            assert len(pieces) > 1  # split at the width step
    assert lens == {80, 128}

    sc_mod.reset_counters()
    on = _reval(db)
    assert on.error is None and on.n_valid == N_BLOCKS
    assert sc_mod.counters()["hit"] == len(imm._chunks)
    monkeypatch.setenv("OCT_SIDECAR", "0")
    off = _reval(db)
    assert (off.n_blocks, off.n_valid, off.error) == \
        (on.n_blocks, on.n_valid, on.error)
    assert off.final_state == on.final_state


def test_resume_across_sidecar_boundary(pristine, tmp_path, monkeypatch):
    """A checkpointed replay resuming from the chunk-0 boundary into a
    store where chunk 0 is UN-sidecared and chunk 1 is sidecared (the
    mixed-generation disk a mid-backfill crash leaves behind) is
    verdict-identical to the uninterrupted run."""
    db = _copy(pristine, tmp_path)
    os.unlink(os.path.join(db, "immutable", "00000.cols"))
    full = _reval(db)
    assert full.error is None and full.n_valid == N_BLOCKS

    imm = ana.open_immutable(db)
    n0 = len(imm._entries[imm._chunks[0]])
    oracle = _prefix_states(db)

    ck = str(tmp_path / "ckpt.json")
    w = recovery.ProgressWriter(ck, recovery.chain_tag(db, PARAMS))
    w.note(oracle[n0], n0)
    monkeypatch.setenv("OCT_CHECKPOINT", ck)
    sc_mod.reset_counters()
    res = ana.revalidate(db, PARAMS, LVIEW, backend="native",
                         validate_all=False, resume=True)
    assert res.resumed_headers == n0
    assert res.error is None and res.n_valid == N_BLOCKS
    assert res.final_state == full.final_state
    c = sc_mod.counters()
    assert c["miss"] >= 1 and c["hit"] >= 1  # crossed the boundary


# ---------------------------------------------------------------------------
# the device-hash lever
# ---------------------------------------------------------------------------


def test_device_hash_spans_matches_host(monkeypatch):
    """OCT_SIDECAR_DEVICE_HASH=1 routes the body-hash batch through the
    Blake2b device kernel (bucket-padded shapes); digests must equal
    hashlib's bit-for-bit, pad lanes dropped."""
    rng = np.random.default_rng(17)
    data = rng.integers(0, 256, size=4096, dtype=np.uint8).tobytes()
    starts = np.asarray([0, 100, 500, 900, 1000], np.int64)
    ends = np.asarray([90, 400, 740, 999, 3000], np.int64)
    host = b2.hash_spans(data, starts, ends)
    monkeypatch.setenv("OCT_SIDECAR_DEVICE_HASH", "1")
    dev = b2.hash_spans(data, starts, ends)
    assert np.array_equal(host, dev)
    assert b2.hash_spans(data, starts[:0], ends[:0]).shape == (0, 32)


# ---------------------------------------------------------------------------
# the native probe primitives + the WALKED seal bit
# ---------------------------------------------------------------------------


def test_native_crc32_matches_zlib():
    """The PCLMULQDQ probe CRC must be bit-identical to ``zlib.crc32``
    on every length class (sub-word tails, the 64-byte fold threshold,
    fold-multiple boundaries) and under chained init values: seals on
    disk may have been written by either implementation and must keep
    verifying under the other."""
    import zlib

    if native_loader.load_crypto() is None:
        pytest.skip("native host-crypto unavailable")
    rng = np.random.default_rng(23)
    for ln in (0, 1, 7, 15, 16, 63, 64, 65, 255, 4096, 70001):
        d = rng.integers(0, 256, size=ln, dtype=np.uint8).tobytes()
        assert native_loader.native_crc32(d) == (zlib.crc32(d) & 0xFFFFFFFF)
    a, b = b"seal " * 31, b"check" * 77
    assert native_loader.native_crc32(b, native_loader.native_crc32(a)) \
        == (zlib.crc32(b, zlib.crc32(a)) & 0xFFFFFFFF)


def test_native_hash_spans_matches_hashlib():
    """``ops/blake2b.hash_spans``' native batch (``oc_blake2b_spans``)
    equals the hashlib loop digest-for-digest — it IS the hot path's
    body-hash compare, so a divergence would silently truncate intact
    chains (or worse, pass rotten ones)."""
    import hashlib

    rng = np.random.default_rng(29)
    data = rng.integers(0, 256, size=65536, dtype=np.uint8).tobytes()
    starts = np.asarray([0, 1, 777, 4000, 65535, 128], np.int64)
    ends = np.asarray([0, 513, 4000, 65536, 65536, 131], np.int64)
    got = b2.hash_spans(data, starts, ends)
    exp = np.stack([
        np.frombuffer(
            hashlib.blake2b(data[s:e], digest_size=32).digest(), np.uint8
        )
        for s, e in zip(starts, ends)
    ])
    assert np.array_equal(got, exp)


def test_walked_seal_provenance_and_differential(pristine, tmp_path):
    """FLAG_WALKED provenance: forge-time seals are WALKED (integrity
    by construction — the replay may skip the per-blob CRC sweep), a
    bare ``backfill_store`` reseal is NOT (no walk backs it, the full
    sweep stays). Both replay to the identical verdict and nonce
    carry."""
    db = _copy(pristine, tmp_path)
    imm_dir = os.path.join(db, "immutable")

    _, _, sc, outcome = _chunk_and_sidecar(db, 0)
    assert outcome == "hit" and sc.walked  # forge-time: by construction

    r_walked = _reval(db)
    assert r_walked.error is None and r_walked.n_valid == N_BLOCKS

    # strip the seal and reseal through a bare writer open: same
    # columns, but nothing walked these bytes — the flag must be OFF
    os.unlink(os.path.join(imm_dir, "00000.cols"))
    imm = ana.open_immutable(db)
    assert sc_mod.backfill_store(imm) == 1
    _, _, sc2, outcome2 = _chunk_and_sidecar(db, 0)
    assert outcome2 == "hit" and not sc2.walked

    r_unwalked = _reval(db)
    assert r_unwalked.error is None
    assert r_unwalked.n_valid == r_walked.n_valid
    assert r_unwalked.final_state == r_walked.final_state
