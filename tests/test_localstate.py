"""Local mini-protocols (LocalStateQuery / LocalTxSubmission /
LocalTxMonitor servers) against a real node kernel, under the sim."""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ledger.extended import ExtLedger
from ouroboros_consensus_tpu.ledger.mock import (
    MockConfig,
    MockLedger,
    encode_tx,
    tx_id,
)
from ouroboros_consensus_tpu.miniprotocol import localstate
from ouroboros_consensus_tpu.node.kernel import NodeKernel
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.instances import PraosProtocol
from ouroboros_consensus_tpu.storage.open import open_chaindb
from ouroboros_consensus_tpu.testing import fixtures
from ouroboros_consensus_tpu.utils.sim import Channel, Recv, Send, Sim

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1),  # every slot forges: deterministic
    epoch_length=1000,
    kes_depth=3,
)


@pytest.fixture
def node(tmp_path):
    pool = fixtures.make_pool(0, kes_depth=3)
    lview = fixtures.make_ledger_view([pool])
    ledger = MockLedger(MockConfig(lview, PARAMS.stability_window))
    proto = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, proto)
    genesis = ext.genesis(ledger.genesis_state([(b"alice", 100)]))
    db = open_chaindb(str(tmp_path), ext, genesis, k=4)
    return NodeKernel("n0", db, proto, ledger, pool=pool)


def drive(server_gen_factory, requests):
    """Run a server task against a scripted client; return replies."""
    rx, tx = Channel(), Channel()
    replies = []

    def client():
        for req in requests:
            yield Send(rx, req)
            if req[0] != "release":
                replies.append((yield Recv(tx)))
        yield Send(rx, ("done",))

    sim = Sim()
    sim.spawn(server_gen_factory(rx, tx), "server")
    sim.spawn(client(), "client")
    sim.run(until=10)
    return replies


def test_state_query(node):
    node.try_forge(0)
    node.try_forge(1)
    replies = drive(
        lambda rx, tx: localstate.state_query_server(node, rx, tx),
        [
            ("acquire", None),
            ("query", "get_chain_block_no", ()),
            ("query", "get_tip_slot", ()),
            ("query", "get_balance", (b"alice",)),
            ("query", "bogus", ()),
        ],
    )
    assert replies[0] == ("acquired",)
    assert replies[1] == ("result", 1)
    assert replies[2] == ("result", 1)
    assert replies[3] == ("result", 100)
    assert replies[4][0] == "failed"


def test_tx_submission_and_monitor(node):
    txin = next(iter(node.chain_db.current_ledger().ledger_state.utxo))
    amt = node.chain_db.current_ledger().ledger_state.utxo[txin][1]
    good = encode_tx([txin], [(b"bob", amt)])
    bad = encode_tx([(b"\x00" * 32, 9)], [(b"x", 1)])
    replies = drive(
        lambda rx, tx: localstate.tx_submission_server(node, rx, tx),
        [("submit", good), ("submit", bad)],
    )
    assert replies[0] == ("accepted",)
    assert replies[1][0] == "rejected"

    replies = drive(
        lambda rx, tx: localstate.tx_monitor_server(node, rx, tx),
        [
            ("acquire",),
            ("has_tx", tx_id(good)),
            ("next_tx",),
            ("next_tx",),
            ("get_sizes",),
        ],
    )
    assert replies[0][0] == "acquired"
    assert replies[1] == ("bool", True)
    assert replies[2] == ("tx", good)
    assert replies[3] == ("no_more",)
    cap, used, n = replies[4][1:]
    assert n == 1 and used == len(good)


def test_tracers():
    from ouroboros_consensus_tpu.utils.trace import (
        Enclose,
        EncloseEvent,
        ListTracer,
        cond_tracer,
        contramap,
        fanout,
    )

    lt = ListTracer()
    t = contramap(lambda e: ("wrapped", e), lt)
    t("x")
    assert lt.events == [("wrapped", "x")]

    lt2 = ListTracer()
    ct = cond_tracer(lambda e: e > 1, lt2)
    ct(1)
    ct(2)
    assert lt2.events == [2]

    lt3 = ListTracer()
    with Enclose(lt3, "op"):
        pass
    assert [e.edge for e in lt3.events] == ["start", "end"]
    assert lt3.events[1].duration >= 0


def test_query_versioning(node):
    """Ledger/Query.hs queryVersion gating: a v1 session cannot name a
    v2 query; the latest version can."""
    import pytest as _pytest

    from ouroboros_consensus_tpu.miniprotocol.localstate import (
        QueryUnsupported,
        run_query,
    )

    st = node.chain_db.current_ledger()
    assert run_query(node, st, "get_tip_slot", (), version=1) is None
    with _pytest.raises(QueryUnsupported):
        run_query(node, st, "get_pool_distr", (), version=1)
    assert run_query(node, st, "get_pool_distr", (), version=2) is not None


# ---------------------------------------------------------------------------
# The Shelley ledger query family (shelley Ledger/Query.hs, v3 vocabulary)
# ---------------------------------------------------------------------------


def _shelley_node(tmp_path):
    from ouroboros_consensus_tpu.ledger import shelley as sh
    from ouroboros_consensus_tpu.protocol.views import hash_key, hash_vrf_vk

    pool = fixtures.make_pool(0, kes_depth=3)
    cred = b"q-cred" + b"\x00" * 22
    pp = sh.PParams(min_fee_a=0, min_fee_b=0, key_deposit=7, pool_deposit=11)
    g = sh.ShelleyGenesis(
        pparams=pp, epoch_length=PARAMS.epoch_length,
        stability_window=PARAMS.stability_window, max_supply=10_000,
    )
    ledger = sh.ShelleyLedger(g)
    st0 = ledger.genesis_state(
        [(b"pay-x", cred, 100)],
        initial_pools=(sh.PoolParams(
            hash_key(pool.vk_cold), hash_vrf_vk(pool.vrf_vk), 0, 0,
            Fraction(0), cred, (),
        ),),
        initial_delegations=((cred, hash_key(pool.vk_cold)),),
    )
    proto = PraosProtocol(PARAMS, use_device_batch=False)
    ext = ExtLedger(ledger, proto)
    genesis = ext.genesis(st0)
    db = open_chaindb(str(tmp_path / "shq"), ext, genesis, k=4)
    return NodeKernel("nq", db, proto, ledger, pool=pool), cred, pool, pp


def test_shelley_query_family(tmp_path):
    from ouroboros_consensus_tpu.protocol.views import hash_key

    node, cred, pool, pp = _shelley_node(tmp_path)
    st = node.chain_db.current_ledger()
    pid = hash_key(pool.vk_cold)
    q = lambda name, *args: localstate.run_query(node, st, name, args)

    assert q("get_epoch_no") == 0
    assert q("get_stake_distribution") == {pid: Fraction(1)}
    assert q("get_stake_pools") == {pid}
    assert q("get_stake_pool_params", [pid])[pid].reward_cred == cred
    assert q("get_current_pparams") == pp
    assert q("get_proposed_pparams_updates") == {}
    assert q("get_rewards", [cred]) == {cred: 0}
    delegs, rewards = q("get_delegations_and_rewards", [cred])
    assert delegs == {cred: pid} and rewards == {cred: 0}
    utxo = q("get_utxo_by_address", [b"pay-x"])
    assert list(utxo.values()) == [((b"pay-x", cred), 100)]
    acct = q("get_account_state")
    assert acct["reserves"] == 10_000 - 100 and acct["treasury"] == 0


def test_shelley_query_era_mismatch_and_versioning(node, tmp_path):
    # era mismatch: the mock-ledger node rejects Shelley queries
    st = node.chain_db.current_ledger()
    with pytest.raises(localstate.EraMismatch):
        localstate.run_query(node, st, "get_epoch_no", ())
    # version gating: v2 clients cannot name v3 queries
    shelley_node, _cred, _pool, _pp = _shelley_node(tmp_path)
    sst = shelley_node.chain_db.current_ledger()
    with pytest.raises(localstate.QueryUnsupported):
        localstate.run_query(shelley_node, sst, "get_epoch_no", (), version=2)
    assert localstate.run_query(
        shelley_node, sst, "get_epoch_no", (), version=3
    ) == 0


def test_query_malformed_args_and_v1_balance_on_shelley(tmp_path):
    """Wrong-arity args get a failure REPLY (not a dead server), and the
    v1 get_balance matches payment addresses on Shelley-era states."""
    node, cred, _pool, _pp = _shelley_node(tmp_path)
    st = node.chain_db.current_ledger()
    assert localstate.run_query(node, st, "get_balance", (b"pay-x",),
                                version=1) == 100

    rx, tx = Channel(), Channel()
    replies = []

    def client():
        yield Send(rx, ("acquire", None))
        replies.append((yield Recv(tx)))
        yield Send(rx, ("query", "get_rewards", ()))  # wrong arity
        replies.append((yield Recv(tx)))
        yield Send(rx, ("query", "get_epoch_no", ()))  # server still alive
        replies.append((yield Recv(tx)))
        yield Send(rx, ("done",))

    sim = Sim()
    sim.spawn(localstate.state_query_server(node, rx, tx, version=3), "s")
    sim.spawn(client(), "c")
    sim.run(until=10)
    assert replies[0][0] == "acquired"
    assert replies[1][0] == "failed" and "takes 1 argument" in replies[1][1]
    assert replies[2] == ("result", 0)


def test_query_arg_shape_validation(tmp_path):
    """A single bytes address where a collection is expected is a CLIENT
    fault (bytes would silently iterate as ints and match nothing);
    get_balance's missing arg is a client fault too, not an internal
    error."""
    node, cred, _pool, _pp = _shelley_node(tmp_path)
    st = node.chain_db.current_ledger()
    with pytest.raises(localstate.QueryError, match="collection"):
        localstate.run_query(node, st, "get_utxo_by_address", (b"pay-x",))
    with pytest.raises(localstate.QueryError, match="takes 1 argument"):
        localstate.run_query(node, st, "get_balance", ())


def test_shelley_query_breadth_round4(tmp_path):
    """The round-4 additions (shelley Ledger/Query.hs parity):
    GetGenesisConfig, GetPoolState, GetStakeSnapshots,
    GetRewardProvenance, DebugNewEpochState."""
    from ouroboros_consensus_tpu.ledger import shelley as sh
    from ouroboros_consensus_tpu.protocol.views import hash_key

    node, cred, pool, pp = _shelley_node(tmp_path)
    st = node.chain_db.current_ledger()
    pid = hash_key(pool.vk_cold)
    q = lambda name, *args: localstate.run_query(node, st, name, args)

    g = q("get_genesis_config")
    assert isinstance(g, sh.ShelleyGenesis) and g.pparams == pp

    ps = q("get_pool_state", [pid, b"\xee" * 28])
    assert set(ps["pools"]) == {pid}
    assert ps["retiring"] == {} and ps["deposits"] == {pid: 0}

    snaps = q("get_stake_snapshots", [pid])
    assert set(snaps) == {"mark", "set", "go"}
    for label in ("mark", "set", "go"):
        assert snaps[label]["pools"][pid] == snaps[label]["total"] == 100

    prov = q("get_reward_provenance")
    assert prov["epoch"] == 0
    assert prov["pots"]["reserves"] == 10_000 - 100
    assert prov["total_go_stake"] == 100

    dump = q("debug_new_epoch_state")
    assert isinstance(dump, sh.ShelleyState)

    # all five are v3-gated like the rest of the family
    with pytest.raises(localstate.QueryUnsupported):
        localstate.run_query(node, st, "get_pool_state", ([pid],), version=2)
    # collection argspec enforced
    with pytest.raises(localstate.QueryError):
        q("get_stake_snapshots", pid)


def test_byron_query_family(tmp_path):
    """Byron-era queries (byron Ledger/Query.hs analog): the delegation
    map + debug dump, era-checked (EraMismatch on a Shelley state)."""
    from fractions import Fraction as F

    from ouroboros_consensus_tpu.ledger.byron import (
        ByronGenesis, ByronLedger, ByronPParams, addr_of,
    )
    from ouroboros_consensus_tpu.ledger.extended import ExtLedger
    from ouroboros_consensus_tpu.ops.host import ed25519 as ed
    from ouroboros_consensus_tpu.protocol.instances import (
        PBftParams, PBftProtocol,
    )
    from ouroboros_consensus_tpu.storage.open import open_chaindb
    from ouroboros_consensus_tpu.hardfork.byron_mock import ByronMockBlock

    gvk = ed.secret_to_public(b"\x10" * 32)
    led = ByronLedger(ByronGenesis(
        pparams=ByronPParams(min_fee_a=0, min_fee_b=0),
        genesis_keys=(gvk,),
    ))
    proto = PBftProtocol(
        PBftParams(num_genesis_keys=1, threshold=F(1), window=5,
                   security_param=4),
        [gvk],
    )
    ext = ExtLedger(led, proto)
    st0 = ext.genesis(led.genesis_state([(addr_of(gvk), 77)]))
    db = open_chaindb(
        str(tmp_path / "bq"), ext, st0, 4,
        decode_block=ByronMockBlock.from_bytes,
    )
    node = NodeKernel("bq", db, proto, led)
    est = db.current_ledger()

    dlg = localstate.run_query(node, est, "get_delegation_map", ())
    assert dlg == {gvk: gvk}
    dump = localstate.run_query(node, est, "get_byron_state", ())
    dump.utxo.clear()  # isolated from the live state
    assert len(db.current_ledger().ledger_state.utxo) == 1
    # era mismatch both directions: byron query on a shelley node...
    sh_node, _c, _p, _pp = _shelley_node(tmp_path)
    with pytest.raises(localstate.EraMismatch):
        localstate.run_query(
            sh_node, sh_node.chain_db.current_ledger(),
            "get_delegation_map", (),
        )
    # ...and a shelley query on this byron node
    with pytest.raises(localstate.EraMismatch):
        localstate.run_query(node, est, "get_epoch_no", ())
