"""Differential tests: device sign-side kernels vs host references.

Covers ops/ed25519_batch.sign (incl. sha512.splice_prefix64 and the
mod-L scalar ops), ops/ecvrf_batch.prove, host/kes.leaf_path signature
assembly, and the db_synthesizer device-VRF span path.
"""

import numpy as np
import pytest

from ouroboros_consensus_tpu.ops import ecvrf_batch, ed25519_batch
from ouroboros_consensus_tpu.ops.host import ecvrf as hv
from ouroboros_consensus_tpu.ops.host import ed25519 as he
from ouroboros_consensus_tpu.ops.host import kes as hk

rng = np.random.default_rng(11)


def _seeds(n):
    return [rng.integers(0, 256, 32, dtype=np.uint8).tobytes() for _ in range(n)]


@pytest.mark.slow
def test_ed25519_sign_matches_host():
    n = 8
    seeds = _seeds(n)
    msgs = [b"m%d" % i * (i + 1) for i in range(n)]  # varied lengths
    sigs = ed25519_batch.sign_batch(seeds, msgs)
    for i in range(n):
        assert sigs[i].tobytes() == he.sign(seeds[i], msgs[i])
        assert he.verify(he.secret_to_public(seeds[i]), msgs[i], sigs[i].tobytes())


@pytest.mark.slow
def test_ecvrf_prove_matches_host():
    # both proof formats share the ONE prove jit (batch_compat only
    # selects which device columns assemble into the proof bytes), so
    # covering draft-03 AND batch-compatible costs one compile
    n = 8
    seeds = _seeds(n)
    alphas = _seeds(n)
    for bc, host_prove in ((False, hv.prove), (True, hv.prove_batch_compat)):
        proofs, betas = ecvrf_batch.prove_batch(seeds, alphas,
                                                batch_compat=bc)
        for i in range(n):
            hp = host_prove(seeds[i], alphas[i])
            assert proofs[i].tobytes() == hp
            assert betas[i].tobytes() == hv.proof_to_hash(hp)


@pytest.mark.slow
def test_kes_leaf_path_assembles_compact_sum():
    depth = 3
    seeds = _seeds(4)
    for i, seed in enumerate(seeds):
        per = int(rng.integers(0, 1 << depth))
        leaf, sibs = hk.leaf_path(seed, depth, per)
        assert len(sibs) == depth
        msg = b"kes-%d" % i
        ed_sig = ed25519_batch.sign_batch([leaf], [msg])[0].tobytes()
        sig = ed_sig + he.secret_to_public(leaf) + b"".join(sibs)
        assert sig == hk.sign(seed, depth, per, msg)
        assert hk.verify(hk.derive_vk(seed, depth), depth, per, msg, sig)


def test_scalar_mod_l_ops():
    import jax

    from ouroboros_consensus_tpu.ops import bigint as bi
    from ouroboros_consensus_tpu.ops import scalar

    L = scalar.L_INT
    vals = [
        (3, 5),
        (L - 1, L - 1),
        (2**255 - 20, L - 2),  # clamped-scalar-sized operand
        (int(rng.integers(0, 2**62)) << 190, 7),
    ]
    a = np.stack([bi.int_to_limbs_np(x, 20) for x, _ in vals])
    b = np.stack([bi.int_to_limbs_np(y, 20) for _, y in vals])
    mul = np.asarray(jax.jit(scalar.mul_mod_l)(a, b))
    add = np.asarray(jax.jit(scalar.add_mod_l)(a % 1 + a, b))  # a, b as-is
    for i, (x, y) in enumerate(vals):
        assert bi.limbs_to_int_np(mul[i]) == (x * y) % L
    # add_mod_l contract is inputs < L: only check those rows
    for i, (x, y) in enumerate(vals):
        if x < L and y < L:
            assert bi.limbs_to_int_np(np.asarray(add[i])) == (x + y) % L


@pytest.mark.slow
def test_synthesizer_device_vrf_span(tmp_path, monkeypatch):
    from ouroboros_consensus_tpu.tools import db_analyser as ana
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    monkeypatch.setattr(synth, "_VRF_BUCKET", 64)  # small compile
    params = synth.default_params(kes_depth=3)
    pools, lview = synth.make_credentials(2, kes_depth=3)
    res = synth.synthesize(
        str(tmp_path / "db"), params, pools, lview,
        synth.ForgeLimit(slots=40), vrf_backend="device",
    )
    assert res.n_blocks > 0
    r = ana.revalidate(str(tmp_path / "db"), params, lview, backend="host")
    assert r.error is None and r.n_valid == res.n_blocks
