"""testing/chaos.py units: the OCT_CHAOS spec grammar (malformed specs
fail LOUDLY — a typo'd fault that silently never fires would fake a
green chaos matrix), per-seam sequence/trigger matching, exactly-once
(and xN) firing semantics, seeded-RNG determinism, and the
zero-overhead-disarmed contract every hot-path seam relies on."""

from __future__ import annotations

import pytest

from ouroboros_consensus_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _disarmed(monkeypatch):
    """Every test starts (and leaves the process) disarmed."""
    monkeypatch.delenv("OCT_CHAOS", raising=False)
    monkeypatch.delenv("OCT_CHAOS_SEED", raising=False)
    chaos.reset()
    yield
    monkeypatch.delenv("OCT_CHAOS", raising=False)
    chaos.reset()


def _arm(monkeypatch, spec: str, seed: int | None = None):
    monkeypatch.setenv("OCT_CHAOS", spec)
    if seed is not None:
        monkeypatch.setenv("OCT_CHAOS_SEED", str(seed))
    chaos.reset()


# ---------------------------------------------------------------------------
# grammar
# ---------------------------------------------------------------------------


def test_parse_every_documented_fault_kind():
    injs = chaos.parse_spec(
        "compile-stall@window:3, device-error@dispatch:2,"
        "staging-thread-death@window:5, sigkill@window:7,"
        "chunk-corrupt@epoch:1, aot-reject@stage:aggregate,"
        "probe-timeout"
    )
    assert [i.kind for i in injs] == [
        "compile-stall", "device-error", "staging-thread-death",
        "sigkill", "chunk-corrupt", "aot-reject", "probe-timeout",
    ]
    # every parsed kind is a documented one, and the registry maps each
    # to at least one seam site
    for i in injs:
        assert i.kind in chaos.FAULT_KINDS
        assert chaos._KIND_SITES[i.kind]
    # the epoch trigger aliases onto the chunk seam key
    assert injs[4].trigger == "chunk" and injs[4].arg == 1
    # stage triggers carry the substring, not an int
    assert injs[5].trigger == "stage" and injs[5].arg == "aggregate"


def test_parse_multiplicity_suffix():
    (inj,) = chaos.parse_spec("device-error@dispatch:2x3")
    assert inj.arg == 2 and inj.count == 3


def test_parse_write_path_fault_kinds():
    """The round-13 durable-store matrix kinds: all placed at the
    chunk writer's append seam (or the marker seam), epoch aliasing
    onto chunk like the read-side grammar."""
    injs = chaos.parse_spec(
        "torn-write@append:4, bitflip@chunk:2, index-truncate@epoch:1,"
        "sigkill@append:3, partial-rename@marker"
    )
    assert [i.kind for i in injs] == [
        "torn-write", "bitflip", "index-truncate", "sigkill",
        "partial-rename",
    ]
    for i in injs[:4]:
        assert "append" in chaos._KIND_SITES[i.kind]
    assert injs[0].trigger == "append" and injs[0].arg == 4
    assert injs[1].trigger == "chunk" and injs[1].arg == 2
    assert injs[2].trigger == "chunk" and injs[2].arg == 1  # epoch alias
    # partial-rename@marker is the documented NO-ARG form: any marker
    # write matches (there is normally exactly one)
    assert injs[4].trigger == "marker" and injs[4].arg is chaos.ANY
    assert injs[4].describe() == "partial-rename@marker"
    # the substring form names a specific marker
    (named,) = chaos.parse_spec("partial-rename@marker:clean")
    assert named.arg == "clean"


def test_parse_serving_plane_fault_sites():
    """The round-20 serving-plane cells: device-error places at the
    shared-window dispatch seam, sigkill at the per-window retire seam
    (AFTER its checkpoint lands) — both fenced off every other kind's
    sites so a serve spec can never satisfy a replay counter."""
    injs = chaos.parse_spec(
        "device-error@serve-dispatch:1, sigkill@serve:2"
    )
    assert injs[0].trigger == "serve-dispatch" and injs[0].arg == 1
    assert injs[1].trigger == "serve" and injs[1].arg == 2
    assert "serve-dispatch" in chaos._KIND_SITES["device-error"]
    assert "serve" in chaos._KIND_SITES["sigkill"]
    # site fencing: the serving seams answer ONLY their own triggers
    assert chaos._SITE_TRIGGER_KEYS["serve"] == ("serve",)
    assert chaos._SITE_TRIGGER_KEYS["serve-dispatch"] == (
        "serve-dispatch",
    )
    # ...and no other fault kind may place at the serving seams
    for kind, sites in chaos._KIND_SITES.items():
        if kind not in ("device-error", "sigkill"):
            assert "serve" not in sites and "serve-dispatch" not in sites


def test_write_path_malformed_specs_fail_loudly():
    # the no-arg sugar belongs to partial-rename@marker ONLY — a bare
    # trigger on any other kind is still the silently-misplaced shape
    with pytest.raises(ValueError, match="empty trigger or arg"):
        chaos.parse_spec("torn-write@append")
    with pytest.raises(ValueError, match="needs a @trigger"):
        chaos.parse_spec("bitflip")


def test_write_fault_matches_and_spends(monkeypatch):
    """write_fault mirrors fire()'s matching at the append site but
    RETURNS the kind (the writer owns the disk mutation): append-order
    and chunk-number triggers both place, exactly once each."""
    _arm(monkeypatch, "torn-write@append:1, bitflip@chunk:7")
    assert chaos.write_fault(chunk=0) is None          # append seq 0
    assert chaos.write_fault(chunk=0) == "torn-write"  # append seq 1
    assert chaos.write_fault(chunk=0) is None          # spent
    assert chaos.write_fault(chunk=7) == "bitflip"     # chunk trigger
    assert chaos.write_fault(chunk=7) is None          # spent
    assert chaos.plan().fired() == [
        "torn-write@append:1", "bitflip@chunk:7",
    ]


def test_partial_rename_fires_only_at_marker_seam(monkeypatch):
    _arm(monkeypatch, "partial-rename@marker")
    chaos.fire("dispatch")  # other seams never detonate it
    assert chaos.write_fault(chunk=0) is None
    with pytest.raises(chaos.PartialRenameChaos):
        chaos.fire("marker", marker="clean")
    chaos.fire("marker", marker="clean")  # spent: a retry succeeds


def test_probe_timeout_rejects_trigger_clause():
    """probe_timeout_pending spends injections in list order, so a
    trigger clause would be silently unhonored — the parser refuses it
    (list the fault N times to kill N attempts instead)."""
    with pytest.raises(ValueError, match="probe-timeout takes no"):
        chaos.parse_spec("probe-timeout@attempt:2")


def test_unsatisfiable_trigger_fails_loudly():
    """A trigger key no seam of the fault's kind ever provides would
    arm and then silently never fire — the fake-green matrix the
    fail-loud rule forbids; refused at parse time instead."""
    with pytest.raises(ValueError, match="can never fire"):
        chaos.parse_spec("torn-write@marker:1")
    with pytest.raises(ValueError, match="can never fire"):
        chaos.parse_spec("partial-rename@chunk:0")
    with pytest.raises(ValueError, match="can never fire"):
        chaos.parse_spec("sigkill@marker:0")
    with pytest.raises(ValueError, match="can never fire"):
        chaos.parse_spec("chunk-corrupt@window:1")
    # every documented placement still parses
    for ok in ("torn-write@append:3", "bitflip@chunk:2", "sigkill@window:2",
               "sigkill@append:15", "index-truncate@epoch:1",
               "partial-rename@marker", "chunk-corrupt@epoch:1",
               "device-error@stage:finish", "device-error@shard:0",
               "compile-stall@window:1", "aot-reject@stage:aggregate"):
        assert chaos.parse_spec(ok)


def test_malformed_specs_fail_loudly():
    with pytest.raises(ValueError, match="unknown fault kind"):
        chaos.parse_spec("device-eror@dispatch:2")
    with pytest.raises(ValueError, match="needs a @trigger"):
        chaos.parse_spec("device-error")
    # an empty arg would parse as the match-ANYTHING '' substring — a
    # silently MIS-PLACED fault, rejected at arm time instead
    with pytest.raises(ValueError, match="empty trigger or arg"):
        chaos.parse_spec("device-error@dispatch")
    with pytest.raises(ValueError, match="empty trigger or arg"):
        chaos.parse_spec("device-error@dispatch:")
    with pytest.raises(ValueError, match="empty trigger or arg"):
        chaos.parse_spec("device-error@:2")
    # and an armed process refuses to start with a broken plan
    import os

    os.environ["OCT_CHAOS"] = "nope@x:1"
    try:
        with pytest.raises(ValueError):
            chaos.reset()
    finally:
        del os.environ["OCT_CHAOS"]
        chaos.reset()


# ---------------------------------------------------------------------------
# firing semantics
# ---------------------------------------------------------------------------


def test_fire_matches_sequence_and_spends_exactly_once(monkeypatch):
    _arm(monkeypatch, "device-error@dispatch:2")
    chaos.fire("dispatch")  # seq 0
    chaos.fire("dispatch")  # seq 1
    with pytest.raises(chaos.DeviceChaosError):
        chaos.fire("dispatch")  # seq 2 -> fires
    # spent: the retried operation succeeds (transient by contract)
    chaos.fire("dispatch")
    assert chaos.plan().fired() == ["device-error@dispatch:2"]


def test_fire_xn_fires_n_times(monkeypatch):
    _arm(monkeypatch, "device-error@dispatch:0x2")
    with pytest.raises(chaos.DeviceChaosError):
        chaos.fire("dispatch")
    # the x2 injection matches the EXPLICIT dispatch key again
    with pytest.raises(chaos.DeviceChaosError):
        chaos.fire("dispatch", dispatch=0)
    chaos.fire("dispatch", dispatch=0)  # both firings spent


def test_stage_substring_trigger(monkeypatch):
    _arm(monkeypatch, "device-error@stage:finish")
    chaos.fire("stage-call", stage="ed")
    chaos.fire("stage-call", stage="kes")
    with pytest.raises(chaos.DeviceChaosError):
        chaos.fire("stage-call", stage="finish")


def test_trigger_key_never_answers_for_another_seams_counter(monkeypatch):
    """device-error is registered at the dispatch, stage-call AND shard
    seams, but a @dispatch trigger must only ever match the dispatch
    seam's OWN counter — on the TPU pk path the stage-call seam fires
    several times per window, and pre-fix it would detonate the fault
    at the wrong seam and spend it (_SITE_SEQ_KEYS regression)."""
    _arm(monkeypatch, "device-error@dispatch:2")
    for _ in range(5):
        chaos.fire("stage-call", stage="ed")  # must NOT detonate
        chaos.fire("shard")  # nor here: @dispatch is not @shard
    chaos.fire("dispatch")  # seq 0
    chaos.fire("dispatch")  # seq 1
    with pytest.raises(chaos.DeviceChaosError):
        chaos.fire("dispatch")  # seq 2: the intended placement
    # and the window alias binds to the dispatch/stage/retire seams
    # only: compile-stall@window:N can never land inside a stage call
    _arm(monkeypatch, "compile-stall@window:0")
    chaos.fire("stage-call", stage="ed")
    assert not chaos.plan().fired()


def test_sites_are_fenced_per_fault_kind(monkeypatch):
    """A spec can never detonate at a seam its fault kind does not
    model: a chunk-corrupt injection is invisible to the dispatch
    seam even at the matching sequence number."""
    _arm(monkeypatch, "chunk-corrupt@epoch:0")
    chaos.fire("dispatch")
    chaos.fire("stage")
    chaos.fire("retire")
    with pytest.raises(chaos.ChunkChaosError):
        chaos.fire("chunk", chunk=0)


def test_explicit_ctx_overrides_seam_sequence(monkeypatch):
    """Seams that know their own index (db_analyser passes chunk=) pin
    the trigger to it — rereads of earlier chunks can't misalign the
    placement."""
    _arm(monkeypatch, "chunk-corrupt@epoch:2")
    chaos.fire("chunk", chunk=0)
    chaos.fire("chunk", chunk=0)  # a reread does not advance toward 2
    chaos.fire("chunk", chunk=1)
    with pytest.raises(chaos.ChunkChaosError):
        chaos.fire("chunk", chunk=2)


def test_compile_stall_sleeps_not_raises(monkeypatch):
    import time

    _arm(monkeypatch, "compile-stall@window:0")
    monkeypatch.setenv("OCT_CHAOS_STALL_S", "0.01")
    t0 = time.monotonic()
    chaos.fire("dispatch")  # sleeps, returns
    assert time.monotonic() - t0 >= 0.01
    assert chaos.plan().fired() == ["compile-stall@window:0"]


def test_aot_reject_message_matches_real_classification(monkeypatch):
    from ouroboros_consensus_tpu.ops.pk import aot

    _arm(monkeypatch, "aot-reject@stage:aggregate")
    with pytest.raises(chaos.AotRejectChaos) as ei:
        chaos.fire("aot", stage="aggregate_core")
    # the injected message IS the r04 failure shape: the real
    # incompatible-executable patterns match it
    assert any(p in str(ei.value).lower()
               for p in aot.INCOMPATIBLE_PATTERNS)


def test_probe_timeout_pending_consumes_one(monkeypatch):
    _arm(monkeypatch, "probe-timeout,probe-timeout")
    assert chaos.probe_timeout_pending()
    assert chaos.probe_timeout_pending()
    assert not chaos.probe_timeout_pending()


# ---------------------------------------------------------------------------
# determinism + disarmed overhead
# ---------------------------------------------------------------------------


def test_seeded_rng_is_deterministic(monkeypatch):
    _arm(monkeypatch, "device-error@dispatch:0", seed=42)
    a = [chaos.rng().random() for _ in range(3)]
    _arm(monkeypatch, "device-error@dispatch:0", seed=42)
    b = [chaos.rng().random() for _ in range(3)]
    assert a == b
    _arm(monkeypatch, "device-error@dispatch:0", seed=43)
    assert [chaos.rng().random() for _ in range(3)] != a


def test_disarmed_fire_is_a_noop_and_counts_nothing():
    assert not chaos.armed() and chaos.plan() is None
    for _ in range(1000):
        chaos.fire("dispatch")
        chaos.fire("stage", stage="ed")
        chaos.fire("retire")
    assert chaos.plan() is None  # no counters, no plan, no state


def test_seams_add_zero_equations_to_production_jaxprs(monkeypatch):
    """The acceptance wording, directly: with the seams in place and
    chaos DISARMED, the seam-adjacent production graphs trace to
    exactly the same equation count as the instrumentation-purity
    baseline (the ratchet in scripts/lint.py re-checks this whenever
    chaos.py/recovery.py change; this is the tier-1 pin)."""
    from ouroboros_consensus_tpu.analysis import graphs

    budgets = graphs.load_budgets()
    names = budgets["instrumentation_purity"]["graphs"]
    assert {"packed_unpack", "verdict_reduce"} <= set(names)
    violations = graphs.check_instrumentation_purity(
        budgets, names=["packed_unpack", "verdict_reduce"]
    )
    assert violations == []
