"""Warm-while-serving compile ladder + threaded staging pipeline
(round-10 tentpole): the differential suite.

The invariants under test:

  * Window RE-TILING never changes semantics — validate_chain with the
    ladder capping windows at a rung, with the staging producer thread
    on or off (all four combinations), produces byte-identical final
    state, identical verdicts, the exact reference error object and the
    same first-failure truncation as the sequential reupdate fold.
  * A MID-CHAIN rung swap (slow-compile stub: the production-bucket
    program's first execute sleeps like a compile wall) changes no
    verdicts, and the swap/bg-compile trajectory is first-class warmup
    forensics.
  * The simulated cold-cache bench harness (stubbed clock via
    OCT_WALL_DEADLINE, as in test_costmodel.py): the replay makes
    progress CONCURRENT with the background production compile, and a
    second run against the same artifact store loads the monolith warm
    with zero doomed deserializes.

Crypto is the hash-only stub (ouroboros_consensus_tpu/testing/stubs)
with the AGGREGATE path active — the ladder only engages on the
aggregate monolith, so the stub agg program rides the real
`_warm_timed` machinery (first-execute labels, store write-back)."""

import os
import time
from dataclasses import replace
from fractions import Fraction

import numpy as np
import pytest

from ouroboros_consensus_tpu.analysis import costmodel
from ouroboros_consensus_tpu.block.forge import forge_block
from ouroboros_consensus_tpu.obs.warmup import WARMUP
from ouroboros_consensus_tpu.ops.pk import aot
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures, stubs

pytestmark = pytest.mark.skipif(
    os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu"),
    reason="CPU differential suite",
)

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=100,
    kes_depth=3,
)


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(60 + i, kes_depth=3) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


def forge_chain(pools, lview, n, first_slot=100):
    """Real-codec bc-proof chain crossing an epoch boundary, with the
    reupdate-fold reference state computed alongside. Slots stay in one
    CBOR width class so every window stages packed (the agg path)."""
    st0 = praos.PraosState(epoch_nonce=b"\x07" * 32)
    st = st0
    hvs, prev = [], b"\xaa" * 32
    slot, blkno = first_slot, 40
    while len(hvs) < n:
        ticked = praos.tick(PARAMS, lview, slot, st)
        blk = forge_block(
            PARAMS, pools[len(hvs) % 2], slot=slot, block_no=blkno,
            prev_hash=prev, epoch_nonce=ticked.state.epoch_nonce,
            txs=(b"t",),
        )
        hv = blk.header.to_view()
        st = praos.reupdate(PARAMS, hv, slot, ticked)
        hvs.append(hv)
        prev = blk.header.hash_
        slot += 1
        blkno += 1
    return st0, hvs, st


@pytest.fixture(scope="module")
def chain(pools, lview):
    st0, hvs, st = forge_chain(pools, lview, 120)
    assert len(hvs[0].vrf_proof) == 128  # batch-compatible (agg path)
    assert PARAMS.epoch_of(hvs[-1].slot) > PARAMS.epoch_of(hvs[0].slot)
    return st0, hvs, st


@pytest.fixture
def fresh_pipeline(monkeypatch):
    """Isolate the process-wide warm state a ladder test mutates:
    warmup recorder, first-execute label sets, the ladder singleton and
    any stub jit entries."""
    WARMUP.reset()
    pbatch.reset_warm_ladder()
    monkeypatch.setattr(pbatch, "_WARM_SEEN", set())
    before = set(pbatch._JIT)
    yield
    for k in set(pbatch._JIT) - before:
        del pbatch._JIT[k]
    pbatch.reset_warm_ladder()
    WARMUP.reset()


def _run_chain(st0, hvs, max_batch=16):
    return pbatch.validate_chain(
        PARAMS, lambda _e: _LVIEW[0], st0, hvs, max_batch=max_batch
    )


_LVIEW = [None]  # set per test (validate_chain takes a callable)


def _rungs(monkeypatch, *rungs):
    monkeypatch.setattr(costmodel, "LADDER_RUNGS", tuple(rungs))


@pytest.mark.parametrize("ladder", ["force", "0"])
@pytest.mark.parametrize("thread", ["1", "0"])
def test_ladder_thread_matrix_equals_fold(pools, lview, chain, monkeypatch,
                                          fresh_pipeline, ladder, thread):
    """All four (ladder x staging-thread) combinations: byte-identical
    final state vs the sequential reupdate fold, across an epoch
    boundary, with the device nonce-scan carry chained throughout."""
    st0, hvs, st_ref = chain
    _LVIEW[0] = lview
    monkeypatch.setenv("OCT_WARM_LADDER", ladder)
    monkeypatch.setenv("OCT_STAGE_THREAD", thread)
    _rungs(monkeypatch, 4)
    stubs.install_stub_crypto(monkeypatch)
    res = _run_chain(st0, hvs)
    assert res.error is None and res.n_valid == len(hvs)
    assert res.state == st_ref
    evs = [e["kind"] for e in WARMUP.report()["ladder"]]
    if ladder == "force":
        assert "engaged" in evs and "bg-compile-started" in evs
    else:
        assert evs == []


@pytest.mark.parametrize("ladder", ["force", "0"])
@pytest.mark.parametrize("thread", ["1", "0"])
def test_matrix_first_failure_truncation(pools, lview, monkeypatch,
                                         fresh_pipeline, ladder, thread):
    """A tampered lane (OCert counter over-increment — a check the
    hash-only stub leaves real) truncates at the SAME position with the
    SAME exact error object in every combination."""
    st0, hvs, _ = forge_chain(pools, lview, 40)
    bad = 23
    hvs[bad] = replace(
        hvs[bad], ocert=replace(hvs[bad].ocert,
                                counter=hvs[bad].ocert.counter + 5)
    )
    _LVIEW[0] = lview
    monkeypatch.setenv("OCT_WARM_LADDER", ladder)
    monkeypatch.setenv("OCT_STAGE_THREAD", thread)
    _rungs(monkeypatch, 4)
    stubs.install_stub_crypto(monkeypatch)
    res = _run_chain(st0, hvs, max_batch=8)
    assert res.n_valid == bad
    assert isinstance(res.error, praos.CounterOverIncrementedOCERT)
    assert res.error == praos.CounterOverIncrementedOCERT(0, 5)


def test_mid_chain_rung_swap_changes_no_verdicts(pools, lview, chain,
                                                 monkeypatch,
                                                 fresh_pipeline):
    """Slow-compile stub: the production-bucket program's first execute
    sleeps (simulated compile wall) while rung windows serve; after the
    background 'compile' lands, the loop swaps to production-sized
    windows mid-replay — final state still byte-identical to the fold,
    and the swap is recorded in the warmup report."""
    from ouroboros_consensus_tpu.utils.trace import (
        LadderEvent, WindowStaged,
    )

    st0, hvs, st_ref = chain
    _LVIEW[0] = lview
    monkeypatch.setenv("OCT_WARM_LADDER", "force")
    monkeypatch.setenv("OCT_STAGE_THREAD", "1")
    _rungs(monkeypatch, 4)
    # target-bucket (16-lane) first execute sleeps 0.4 s — rung windows
    # (padded to 8 lanes) compile instantly
    stubs.install_stub_crypto(
        monkeypatch, agg_delay_s=lambda lanes: 0.4 if lanes >= 16 else 0.0
    )
    events = []
    prev_tracer = pbatch.BATCH_TRACER
    pbatch.set_batch_tracer(lambda ev: events.append(ev))
    try:
        res1 = _run_chain(st0, hvs[:60])
        assert res1.error is None and res1.n_valid == 60
        lad = pbatch._LADDER
        assert lad is not None
        assert lad._done.wait(5.0)  # background compile lands
        res2 = _run_chain(res1.state, hvs[60:])
        assert res2.error is None and res2.n_valid == 60
        assert res2.state == st_ref
    finally:
        pbatch.set_batch_tracer(prev_tracer)
    kinds = [e.kind for e in events if isinstance(e, LadderEvent)]
    assert "engaged" in kinds and "bg-compile-started" in kinds
    assert "swap" in kinds
    report = WARMUP.report()["ladder"]
    assert any(e["kind"] == "swap" for e in report)
    assert any(e["kind"] == "bg-compile-done" for e in report)
    # the re-tiling is VISIBLE: rung-capped windows before the swap,
    # production-sized windows after it
    staged = [e for e in events if isinstance(e, WindowStaged)]
    assert any(e.lanes <= 4 for e in staged), "no rung-sized window"
    assert any(e.lanes > 4 for e in staged), "never re-tiled to production"


def test_cold_cache_harness_overlaps_and_reloads_warm(
        pools, lview, chain, monkeypatch, fresh_pipeline, tmp_path):
    """The simulated cold-cache bench harness (stubbed clock +
    slow-compile stub, as in test_costmodel.py):

      1. auto-mode ladder engages because the aggregate monolith is
         predicted over the remaining $OCT_WALL_DEADLINE;
      2. replay progress is CONCURRENT with the background compile —
         a rung window's first execute lands before bg-compile-done;
      3. the run completes well inside the wall (the provisional
         checkpoint would have banked);
      4. a SECOND run against the same artifact store loads the
         production program warm: via=xla-aot, zero doomed
         deserializes (no failed/rejected/wrong_build outcomes)."""
    st0, hvs, st_ref = chain
    _LVIEW[0] = lview
    monkeypatch.delenv("OCT_WARM_LADDER", raising=False)  # auto mode
    monkeypatch.setenv("OCT_STAGE_THREAD", "1")
    monkeypatch.setenv("OCT_PK_AOT_DIR", str(tmp_path))
    monkeypatch.setenv("OCT_PK_AOT_WRITEBACK", "1")
    # XLA:CPU cannot round-trip serialized executables for large fused
    # programs ("Symbols not found" at deserialize — a backend
    # limitation; TPU PJRT serialization is the production path, and
    # test_aot_latch covers the REAL roundtrip with small executables).
    # Fake ONLY the PJRT serialization layer; every store mechanism —
    # manifest, provenance, markers, memoization — stays real.
    from jax.experimental import serialize_executable as se

    exec_reg: dict = {}

    def fake_serialize(compiled):
        token = b"tok%d" % len(exec_reg)
        exec_reg[token] = compiled
        return token, None, None

    monkeypatch.setattr(se, "serialize", fake_serialize)
    monkeypatch.setattr(se, "deserialize_and_load",
                        lambda ser, it, ot: exec_reg[ser])
    monkeypatch.setattr(aot, "_LOADED", {})
    monkeypatch.setattr(aot, "_MANIFEST_CACHE", {})
    _rungs(monkeypatch, 4, 8)
    # stubbed clock: 300 s of wall; the monolith predicted 500 s (does
    # not fit -> ladder engages), rung programs predicted cheap (fit ->
    # choose_rung picks the LARGEST rung)
    monkeypatch.setenv("OCT_WALL_DEADLINE", str(time.time() + 300.0))
    pred = {"aggregate_core": 500.0, "verify_praos_core_bc": 400.0}
    monkeypatch.setattr(costmodel, "predicted_wall",
                        lambda g: pred.get(g, 1.0))
    real_pinned = costmodel.pinned
    monkeypatch.setattr(
        costmodel, "pinned",
        lambda n: ({"feature_hash": "rungpin"} if "@" in n
                   else real_pinned(n)),
    )
    stubs.install_stub_crypto(
        monkeypatch, agg_delay_s=lambda lanes: 0.4 if lanes >= 16 else 0.0
    )
    t0 = time.monotonic()
    res = _run_chain(st0, hvs)
    wall = time.monotonic() - t0
    assert res.error is None and res.n_valid == len(hvs)
    assert res.state == st_ref
    assert wall < 60.0  # trivially inside the 300 s stubbed wall
    lad = pbatch._LADDER
    assert lad is not None and lad._done.wait(10.0)
    report = WARMUP.report()
    lad_evs = {e["kind"]: e for e in report["ladder"]}
    assert "engaged" in lad_evs
    assert lad_evs["engaged"]["rung"] == 8  # largest rung that fits
    assert "bg-compile-done" in lad_evs
    # replay progress concurrent with the background compile: a RUNG
    # window's first execute landed before the bg compile did
    rung_stages = [
        v for k, v in report["stages"].items()
        if k.startswith("agg-packed:") and ":16l" not in k
    ]
    assert rung_stages, report["stages"]
    assert min(s["t"] for s in rung_stages) < lad_evs["bg-compile-done"]["t"]
    # the write-back banked the production program: a fresh process
    # (fresh warm/label state) loads it from the store
    saved = [e for e in report["aot_events"] if e["outcome"] == "saved"]
    assert saved, report["aot_events"]
    WARMUP.reset()
    pbatch.reset_warm_ladder()
    monkeypatch.setattr(pbatch, "_WARM_SEEN", set())
    monkeypatch.setattr(aot, "_LOADED", {})
    monkeypatch.setattr(aot, "_MANIFEST_CACHE", {})
    monkeypatch.delenv("OCT_WALL_DEADLINE", raising=False)
    res2 = _run_chain(st0, hvs)
    assert res2.error is None and res2.state == st_ref
    rep2 = WARMUP.report()
    outcomes = rep2["aot"]
    assert outcomes.get("loaded", 0) >= 1
    for bad in ("failed", "rejected", "wrong_build", "marker_skip"):
        assert outcomes.get(bad, 0) == 0, rep2["aot_events"]
    assert any(v.get("via") == "xla-aot" for v in rep2["stages"].values())


def test_choose_rung_against_deadline(monkeypatch):
    """costmodel.choose_rung: largest pinned rung that fits the
    remaining deadline with margin; smallest when none fit; largest
    when no deadline is exported."""
    monkeypatch.setattr(
        costmodel, "predicted_wall",
        lambda g: {"aggregate_core@1024": 10.0,
                   "aggregate_core@2048": 200.0}.get(g),
    )
    monkeypatch.delenv("OCT_WALL_DEADLINE", raising=False)
    assert costmodel.choose_rung("aggregate_core",
                                 rungs=(1024, 2048)) == 2048
    monkeypatch.setenv("OCT_WALL_DEADLINE", str(1000.0))
    # 100 s left: 10+30 fits, 200+30 does not
    assert costmodel.choose_rung("aggregate_core", now=900.0,
                                 rungs=(1024, 2048)) == 1024
    # 10 s left: nothing fits -> smallest rung
    assert costmodel.choose_rung("aggregate_core", now=990.0,
                                 rungs=(1024, 2048)) == 1024
    # 400 s left: both fit -> largest
    assert costmodel.choose_rung("aggregate_core", now=600.0,
                                 rungs=(1024, 2048)) == 2048


def test_ladder_pins_are_shipped():
    """Every rung program the ladder may compile is pinned in
    costmodel.json AND fenced by a budgets.json compile_wall ceiling
    (lint exit 5 enforces the ratchet; this pins the shipped state)."""
    from ouroboros_consensus_tpu.analysis import graphs

    cost = costmodel.load_cost()
    budgets = graphs.load_budgets()
    wall = budgets["compile_wall"]["graphs"]
    for pin_name, base, lanes in costmodel.ladder_pins():
        assert pin_name in cost["graphs"], pin_name
        assert pin_name in wall, pin_name
        assert cost["graphs"][pin_name]["predicted_s"] > 0
    # the honest structural fact the pins record on this snapshot: the
    # composed graphs are lane-invariant, so a rung pin hashes equal to
    # its base graph's — if a kernel change ever makes the structure
    # lane-sensitive, THIS is where it shows up first
    for pin_name, base, lanes in costmodel.ladder_pins():
        assert "feature_hash" in cost["graphs"][pin_name]


def test_stage_pin_graph_resolution(monkeypatch):
    real_pinned = costmodel.pinned
    monkeypatch.setattr(
        costmodel, "pinned",
        lambda n: ({"feature_hash": "x"} if n == "aggregate_core@1024"
                   else real_pinned(n)),
    )
    s = "agg-packed:410b:scan:1024l"
    assert costmodel.stage_graph(s) == "aggregate_core"
    assert costmodel.stage_pin_graph(s, 1024) == "aggregate_core@1024"
    assert costmodel.stage_pin_graph(s, 512) == "aggregate_core"
    assert costmodel.stage_pin_graph(s, None) == "aggregate_core"


def test_staging_thread_overlaps_device_wait(pools, lview, chain,
                                             monkeypatch, fresh_pipeline):
    """The mechanism itself, timestamp-proven (ratio-free — a 1-core
    box can't show wall-clock speedup): with OCT_STAGE_THREAD=1,
    prepare_window runs on the producer thread and at least one
    staging call STARTS while the main thread is blocked inside a
    device wait; with =0 every prepare runs inline on the main
    thread."""
    import threading

    st0, hvs, st_ref = chain
    _LVIEW[0] = lview
    monkeypatch.setenv("OCT_WARM_LADDER", "0")
    stubs.install_stub_crypto(monkeypatch)

    prep_calls: list = []
    orig_prep = pbatch.prepare_window

    def traced_prep(*a, **k):
        t0 = time.monotonic()
        out = orig_prep(*a, **k)
        prep_calls.append(
            (threading.current_thread().name, t0, time.monotonic())
        )
        return out

    monkeypatch.setattr(pbatch, "prepare_window", traced_prep)
    waits: list = []
    orig_mat = pbatch.materialize_verdicts

    def slow_mat(tagged, b):
        t0 = time.monotonic()
        time.sleep(0.05)  # the simulated device wait (GIL released)
        out = orig_mat(tagged, b)
        waits.append((t0, time.monotonic()))
        return out

    monkeypatch.setattr(pbatch, "materialize_verdicts", slow_mat)

    monkeypatch.setenv("OCT_STAGE_THREAD", "1")
    res = _run_chain(st0, hvs, max_batch=16)
    assert res.error is None and res.state == st_ref
    assert all(name.startswith("oct-stage") for name, _, _ in prep_calls)
    overlapped = [
        1 for _name, p0, p1 in prep_calls
        for w0, w1 in waits
        if max(p0, w0) < min(p1, w1)
    ]
    assert overlapped, "no staging call overlapped a device wait"

    prep_calls.clear()
    waits.clear()
    monkeypatch.setenv("OCT_STAGE_THREAD", "0")
    res = _run_chain(st0, hvs, max_batch=16)
    assert res.error is None and res.state == st_ref
    assert prep_calls
    assert all(name == "MainThread" for name, _, _ in prep_calls)
