"""Native chunk scanner: differential vs the pure-Python CBOR parser."""

from fractions import Fraction

import numpy as np
import pytest

from ouroboros_consensus_tpu import native_loader
from ouroboros_consensus_tpu.block.forge import forge_block
from ouroboros_consensus_tpu.block.praos_block import Block
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1),
    epoch_length=1000,
    kes_depth=3,
)


@pytest.fixture(scope="module")
def chunk():
    pool = fixtures.make_pool(0, kes_depth=3)
    nonce = b"\x07" * 32
    blocks, prev = [], None
    for s in range(6):
        b = forge_block(
            PARAMS, pool, slot=s, block_no=s, prev_hash=prev,
            epoch_nonce=nonce, txs=(b"tx-%d" % s,),
        )
        blocks.append(b)
        prev = b.hash_
    return b"".join(b.bytes_ for b in blocks), blocks


def require_native():
    if native_loader.load() is None:
        pytest.skip("native library unavailable (no g++?)")


def test_scan_items(chunk):
    require_native()
    buf, blocks = chunk
    offsets, sizes, end = native_loader.scan_items(buf)
    assert len(offsets) == len(blocks)
    assert end == len(buf)
    pos = 0
    for off, sz, b in zip(offsets, sizes, blocks):
        assert off == pos and sz == len(b.bytes_)
        pos += sz


def test_scan_detects_corruption(chunk):
    buf, blocks = chunk
    require_native()
    cut = buf[: len(buf) - 10]  # torn tail
    offsets, sizes, end = native_loader.scan_items(cut)
    assert len(offsets) == len(blocks) - 1
    assert end == sum(len(b.bytes_) for b in blocks[:-1])


def test_extract_headers_matches_python(chunk):
    require_native()
    buf, blocks = chunk
    offsets, sizes, _ = native_loader.scan_items(buf)
    cols = native_loader.extract_headers(buf, offsets)
    assert cols.n == len(blocks)
    for i, blk in enumerate(blocks):
        body = blk.header.body
        assert cols.block_no[i] == body.block_no
        assert cols.slot[i] == body.slot
        if body.prev_hash is None:
            assert cols.has_prev[i] == 0
        else:
            assert cols.has_prev[i] == 1
            assert bytes(cols.prev_hash[i]) == body.prev_hash
        assert bytes(cols.issuer_vk[i]) == body.issuer_vk
        assert bytes(cols.vrf_vk[i]) == body.vrf_vk
        assert bytes(cols.vrf_output[i]) == body.vrf_output
        # the proof column is 128-wide zero-padded; per-row length
        # discriminates the format (80 draft-03 / 128 batch-compatible)
        assert cols.vrf_proof_len[i] == len(body.vrf_proof)
        assert (bytes(cols.vrf_proof[i][: cols.vrf_proof_len[i]])
                == body.vrf_proof)
        assert bytes(cols.body_hash[i]) == body.body_hash
        assert bytes(cols.ocert_vk[i]) == body.ocert.vk_hot
        assert cols.ocert_counter[i] == body.ocert.counter
        assert cols.ocert_kes_period[i] == body.ocert.kes_period
        assert cols.ocert_sigma[i] == body.ocert.sigma
        assert (cols.pv_major[i], cols.pv_minor[i]) == body.protocol_version
        assert cols.kes_sig[i] == blk.header.kes_sig
        # the signed span must be byte-identical to the memoised encoding
        assert cols.signed_bytes[i] == body.signed_bytes


def test_extract_rejects_garbage():
    require_native()
    with pytest.raises(ValueError):
        native_loader.extract_headers(b"\x82\x00\x00", np.array([0], np.int64))


def test_native_reparse_matches_python(chunk, tmp_path):
    """ImmutableDB index rebuild: native scanner and pure-Python walk
    must produce identical entries (incl. header hashes)."""
    require_native()
    import os

    from ouroboros_consensus_tpu.storage.immutable import ImmutableDB

    buf, blocks = chunk
    for sub, native in (("n", True), ("p", False)):
        d = str(tmp_path / sub)
        os.makedirs(d)
        with open(os.path.join(d, "00000.chunk"), "wb") as f:
            f.write(buf)
        if not native:
            import ouroboros_consensus_tpu.storage.immutable as imm_mod

            orig = imm_mod.ImmutableDB._reparse_chunk_native
            imm_mod.ImmutableDB._reparse_chunk_native = lambda self, n, data: None
            try:
                db = ImmutableDB(d)
            finally:
                imm_mod.ImmutableDB._reparse_chunk_native = orig
        else:
            db = ImmutableDB(d)
        entries = db._entries[0]
        assert [e.hash_ for e in entries] == [b.hash_ for b in blocks]
        assert [e.slot for e in entries] == [b.slot for b in blocks]
