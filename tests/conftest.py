"""Test configuration.

Tests run on a virtual 8-device CPU mesh (multi-chip hardware is not
available in CI): the env vars must be set before jax is first imported,
hence this conftest sets them at collection time. The real-TPU benchmark
path is exercised separately by bench.py.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Some environments install a sitecustomize that force-registers a TPU
# plugin and overrides jax_platforms after interpreter start; the config
# update below (post-import, pre-backend-init) wins either way.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# the crypto kernels are large HLO graphs: cache compilations across runs
# (must go through jax.config — env vars are ignored after `import jax`)
jax.config.update("jax_compilation_cache_dir", "/tmp/ouroboros-jax-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")
