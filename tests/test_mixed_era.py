"""Mixed-era composite (BASELINE config 5): synthesize a ByronMock →
Shelley(TPraos) → Babbage(Praos) chain crossing both boundaries, then
revalidate it through the HFC with every backend — differential
host vs device vs native."""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.hardfork import byron_mock, composite, combinator
from ouroboros_consensus_tpu.protocol import tpraos


CFG = composite.CardanoMockConfig(
    byron_epochs=1,
    byron_epoch_length=30,
    shelley_epochs=2,
    epoch_length=40,
    n_delegs=2,
    shelley_d=Fraction(1, 2),
    k=5,
    kes_depth=3,
)
N_SLOTS = 30 + 2 * 40 + 35  # byron + shelley + a good chunk of babbage


@pytest.fixture(scope="module")
def chain(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("mixed") / "db")
    n = composite.synthesize(path, CFG, N_SLOTS)
    return path, n


def test_synthesize_crosses_both_boundaries(chain):
    path, n = chain
    res = composite.revalidate(path, CFG, backend="host")
    assert res.error is None, repr(res.error)
    assert res.n_valid == res.n_blocks == n
    assert set(res.per_era) == {"byron", "shelley", "babbage"}
    assert all(v > 0 for v in res.per_era.values()), res.per_era
    assert res.final_state.era == 2


@pytest.mark.slow  # the device backend's XLA-twin compile is the bulk
# of this file's wall time; host-vs-native agreement stays default-tier
# via test_synthesize_crosses_both_boundaries + the five-era tests
def test_backends_agree(chain):
    path, n = chain
    results = {
        b: composite.revalidate(path, CFG, backend=b)
        for b in ("host", "native", "device")
    }
    for b, r in results.items():
        assert r.error is None, (b, r.error)
        assert r.n_valid == n, b
    # identical final protocol state across backends
    h = results["host"].final_state
    assert results["native"].final_state == h
    assert results["device"].final_state == h


def test_tampered_byron_block_rejected(chain, tmp_path):
    import glob
    import os
    import shutil

    path, n = chain
    bad = str(tmp_path / "bad")
    shutil.copytree(path, bad)
    # flip a bit inside the first chunk (the byron segment)
    chunk = sorted(glob.glob(os.path.join(bad, "immutable", "*.chunk")))[0]
    with open(chunk, "r+b") as f:
        f.seek(40)
        b = f.read(1)
        f.seek(40)
        f.write(bytes([b[0] ^ 0x01]))
    rh = composite.revalidate(bad, CFG, backend="host")
    rd = composite.revalidate(bad, CFG, backend="device")
    # both reject at the same position with the same class (or both fail
    # to decode the torn block identically)
    assert (rh.error is None) == (rd.error is None)
    assert rh.n_valid == rd.n_valid
    if rh.error is not None:
        assert type(rh.error) is type(rd.error)


def test_era_tagged_roundtrip():
    blk = byron_mock.forge_block(
        b"\x01" * 32, slot=3, block_no=0, prev_hash=None, txs=(b"t",)
    )
    hfb = combinator.HardForkBlock(0, blk)
    out = combinator.decode_block(
        hfb.bytes_, [byron_mock.ByronMockBlock.from_bytes]
    )
    assert out.era == 0 and out.block == blk
    assert out.block.check_integrity()


def test_shelley_nonce_continuity(chain):
    """The Babbage epoch nonce descends from Shelley's evolution (the
    TPraos→Praos translation carries nonces; Translate.hs)."""
    path, n = chain
    res = composite.revalidate(path, CFG, backend="host")
    st = res.final_state
    assert st.inner.epoch_nonce is not None
    assert st.inner.evolving_nonce is not None


# -- 5-era composite (VERDICT r2 item 8) -------------------------------------

CFG5 = composite.CardanoMockConfig(
    byron_epochs=1,
    byron_epoch_length=30,
    shelley_epochs=2,
    epoch_length=40,
    n_delegs=2,
    shelley_d=Fraction(1, 2),
    k=5,
    kes_depth=3,
    # Conway: DOUBLED epoch length and f=1/2 (a real lottery);
    # Leios: epoch length changes again, back to f=1
    conway_epochs=1,       # babbage runs one epoch before conway
    conway_f=Fraction(1, 2),
    conway_epoch_length=80,
    leios_epochs=1,        # conway runs one (80-slot) epoch before leios
    leios_f=Fraction(1),
    leios_epoch_length=20,
)
# byron 30 + shelley 80 + babbage 40 + conway 80 + some leios
N_SLOTS5 = 30 + 80 + 40 + 80 + 45


@pytest.fixture(scope="module")
def chain5(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("mixed5") / "db")
    n = composite.synthesize(path, CFG5, N_SLOTS5)
    return path, n


def test_five_era_synthesize_and_revalidate(chain5):
    """5-era chain (PBFT -> TPraos -> Praos -> Praos' -> Praos'') with
    per-era epoch length AND active-slot-coefficient changes crosses
    all four boundaries and revalidates clean (Cardano/Block.hs:96,
    CanHardFork.hs:273 shape)."""
    path, n = chain5
    res = composite.revalidate(path, CFG5, backend="native")
    assert res.error is None, repr(res.error)
    assert res.n_valid == res.n_blocks == n
    assert set(res.per_era) == {"byron", "shelley", "babbage", "conway", "leios"}
    # conway ran a real f=1/2 lottery: strictly fewer blocks than slots
    assert 0 < res.per_era["conway"] < 80
    # every other Praos-class era is full-occupancy (f=1, minus the
    # TPraos overlay's inactive slots in shelley)
    assert res.per_era["leios"] > 0
    assert res.per_era["babbage"] == 40


def test_five_era_tamper_detected_in_conway(chain5, tmp_path):
    """A corrupted block inside the 4th era is caught by revalidation."""
    import os
    import shutil

    path, n = chain5
    cpath = str(tmp_path / "tampered")
    shutil.copytree(path, cpath)
    # find a chunk holding conway blocks (slots 150..230) and flip a bit
    from ouroboros_consensus_tpu.storage.immutable import ImmutableDB

    imm = ImmutableDB(os.path.join(cpath, "immutable"))
    target = None
    for e in imm.iter_entries():
        if 155 <= e.slot < 225:
            target = e
            break
    assert target is not None
    import glob

    chunk_files = sorted(glob.glob(os.path.join(cpath, "immutable", "*.chunk")))
    # locate the chunk containing the target offset (chunk files are
    # sequential; entry offsets are file-relative) — flip a byte in the
    # middle of the target entry
    for cf in chunk_files:
        size = os.path.getsize(cf)
        # entries know their chunk via the DB internals; easiest: try
        # flipping in each file at the entry offset and accept the one
        # that changes revalidation
        if target.offset + 16 < size:
            data = bytearray(open(cf, "rb").read())
            data[target.offset + 12] ^= 0x01
            open(cf, "wb").write(bytes(data))
            break
    res = composite.revalidate(cpath, CFG5, backend="native")
    assert res.error is not None or res.n_valid < n


# ---------------------------------------------------------------------------
# Ledger-backed composite: real Byron UTxO -> Shelley STS -> Mary-class
# ---------------------------------------------------------------------------

LEDGER_CFG = composite.CardanoMockConfig(
    byron_epochs=1,
    byron_epoch_length=40,
    shelley_epochs=2,
    epoch_length=40,  # byron ends at 40 = a shelley epoch boundary
    n_delegs=2,
    shelley_d=Fraction(1, 2),
    k=5,
    kes_depth=3,
    with_ledgers=True,
)
LEDGER_N_SLOTS = 40 + 2 * 40 + 30


@pytest.fixture(scope="module")
def ledger_chain(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("mixed_ledger") / "db")
    n = composite.synthesize(path, LEDGER_CFG, LEDGER_N_SLOTS)
    return path, n


def test_ledger_backed_chain_moves_value_across_all_eras(ledger_chain):
    """VERDICT r3 items 5+6: era-0 (real Byron rules) txs move value
    that is STILL SPENDABLE after the Byron->Shelley translation and
    again after the Shelley->Mary translation; the Mary-class segment
    mints a native asset. The whole chain revalidates end-to-end with
    full rule application (witnesses, fees, conservation)."""
    from ouroboros_consensus_tpu.ledger.mary import MaryValue, policy_id
    from ouroboros_consensus_tpu.ledger.shelley import ShelleyState
    from ouroboros_consensus_tpu.ops.host import ed25519 as ed

    path, n = ledger_chain
    res = composite.revalidate(path, LEDGER_CFG, backend="host")
    assert res.error is None, repr(res.error)
    assert res.n_valid == res.n_blocks == n
    assert set(res.per_era) == {"byron", "shelley", "babbage"}

    lst = res.final_ledger_state
    assert lst.era == 2 and isinstance(lst.inner, ShelleyState)
    # exactly one live output: the value chain's head, fee-decremented
    # by every Byron tx, carrying the minted asset
    [(addr, val)] = list(lst.inner.utxo.values())
    n_byron_txs = sum(
        1 for s in range(1, 40) if s % LEDGER_CFG.byron_epoch_length != 0
    )
    cm = composite.CardanoMock(LEDGER_CFG)
    expected = (
        cm.LEDGER_GENESIS_COIN - n_byron_txs * cm.LEDGER_BYRON_FEE
    )
    assert int(val) == expected
    pid = policy_id(ed.secret_to_public(cm.MINT_POLICY_SEED))
    assert isinstance(val, MaryValue)
    assert val.asset_map() == {(pid, cm.MINT_ASSET): 1_000}
    # Byron's fee pot folded into Shelley reserves at the boundary:
    # conservation over the whole composite
    total = int(val) + lst.inner.fees + lst.inner.prev_fees + \
        lst.inner.reserves + lst.inner.treasury + lst.inner.deposits
    assert total == cm.shelley_ledger.genesis.max_supply


def test_ledger_backed_chain_rejects_tampered_tx(ledger_chain, tmp_path):
    """Corrupting one Byron tx's witness makes the LEDGER replay fail
    even though the consensus (header) checks still pass."""
    import glob
    import shutil

    from ouroboros_consensus_tpu.ledger.byron import ByronInvalidWitness
    from ouroboros_consensus_tpu.utils import cbor as cbor_mod

    path, _n = ledger_chain
    broken = str(tmp_path / "broken")
    shutil.copytree(path, broken)
    cm = composite.CardanoMock(LEDGER_CFG)
    from ouroboros_consensus_tpu.storage.immutable import ImmutableDB

    imm = ImmutableDB(broken + "/immutable")
    blocks = [
        combinator.decode_block(raw, cm.decoders)
        for _e, raw in imm.stream_all()
    ]
    # find the first Byron tx-bearing block and flip a witness bit
    target = next(
        b for b in blocks if b.era == 0 and b.block.txs
    )
    tag, body = cbor_mod.decode(target.block.txs[0])
    assert tag == 0
    ins, outs, wits = body
    vk, sig = wits[0]
    bad_payload = cbor_mod.encode(
        [0, [ins, outs, [[vk, sig[:-1] + bytes([sig[-1] ^ 1])]]]]
    )

    lst = cm.ledger_genesis_state()
    ticked = cm.hf_ledger.tick(lst, target.slot)

    class _B:
        slot = target.slot
        txs = (bad_payload,)
        header = target.block.header

    with pytest.raises(ByronInvalidWitness):
        cm.hf_ledger.apply_block(ticked, composite.HardForkBlock(0, _B()))


def test_ledger_backed_revalidate_reports_ledger_error(tmp_path):
    """A chain whose headers pass consensus but whose body breaks the
    LEDGER rules reports through MixedResult.error (the db-analyser
    contract), not an uncaught exception."""
    from ouroboros_consensus_tpu.ledger import byron as byron_led
    from ouroboros_consensus_tpu.ledger.byron import ByronInvalidWitness
    from ouroboros_consensus_tpu.storage.immutable import ImmutableDB

    cm = composite.CardanoMock(LEDGER_CFG)
    path = str(tmp_path / "bad")
    import os

    os.makedirs(path, exist_ok=True)
    imm = ImmutableDB(path + "/immutable", chunk_size=100)

    ebb = byron_mock.forge_ebb(slot=0, block_no=0, prev_hash=None)
    hfb = composite.HardForkBlock(0, ebb)
    imm.append_block(0, 0, hfb.hash_, hfb.bytes_)

    # a consensus-valid delegate block carrying a corrupted-witness tx
    good_tx = byron_led.make_tx(
        [(bytes(32), 0)],
        [(composite._LedgerTxChain(cm).addr,
          cm.LEDGER_GENESIS_COIN - cm.LEDGER_BYRON_FEE)],
        [cm.LEDGER_SPEND_SEED],
    )
    p = byron_led.decode_payload(good_tx)
    vk, sig = p.witnesses[0]
    bad_tx = byron_led.encode_tx(
        p.ins, p.outs, [(vk, sig[:-1] + bytes([sig[-1] ^ 1]))]
    )
    blk = byron_mock.forge_block(
        cm.delegs[1].cold_seed, slot=1, block_no=0, prev_hash=hfb.hash_,
        txs=(bad_tx,),
    )
    hfb2 = composite.HardForkBlock(0, blk)
    imm.append_block(1, 0, hfb2.hash_, hfb2.bytes_)
    imm.flush()

    res = composite.revalidate(path, LEDGER_CFG, backend="host")
    assert isinstance(res.error, ByronInvalidWitness), repr(res.error)
    assert res.final_ledger_state is not None


def test_five_era_ledger_backed_chain(tmp_path):
    """ALL FIVE eras with real ledgers: Byron UTxO -> Shelley STS ->
    Mary-class x3, where Conway DOUBLES the epoch length and Leios
    changes it again — the era-relative ShelleyGenesis (EpochInfo-from-
    Summary seam) keeps every era's epoch arithmetic sound across two
    mid-chain epoch-length changes; the era-0 value (and the era-2
    minted asset) survive FOUR translations."""
    from ouroboros_consensus_tpu.ledger.mary import MaryValue, policy_id
    from ouroboros_consensus_tpu.ledger.shelley import ShelleyState
    from ouroboros_consensus_tpu.ops.host import ed25519 as ed

    cfg = composite.CardanoMockConfig(
        byron_epochs=1,
        byron_epoch_length=40,
        shelley_epochs=1,
        epoch_length=40,
        conway_epochs=1,          # babbage runs 1 epoch before conway
        conway_epoch_length=80,   # DOUBLED mid-chain
        leios_epochs=1,           # conway runs 1 epoch before leios
        leios_epoch_length=20,    # changed again
        n_delegs=2,
        shelley_d=Fraction(1, 2),
        k=5,
        kes_depth=3,
        with_ledgers=True,
    )
    # byron 40 + shelley 40 + babbage 40 + conway 80 + some leios
    n_slots = 40 + 40 + 40 + 80 + 30
    path = str(tmp_path / "db")
    n = composite.synthesize(path, cfg, n_slots)
    res = composite.revalidate(path, cfg, backend="host")
    assert res.error is None, repr(res.error)
    assert res.n_valid == res.n_blocks == n
    assert set(res.per_era) == {
        "byron", "shelley", "babbage", "conway", "leios"
    }

    lst = res.final_ledger_state
    assert lst.era == 4 and isinstance(lst.inner, ShelleyState)
    cm = composite.CardanoMock(cfg)
    # leios's era-relative epoch count: summary start epoch + elapsed
    leios_gen = cm.eras[4].ledger.genesis
    assert leios_gen.era_start_slot == 200 and leios_gen.epoch_length == 20
    assert lst.inner.epoch == leios_gen.epoch_of_slot(n_slots - 1)
    [(addr, val)] = list(lst.inner.utxo.values())
    pid = policy_id(ed.secret_to_public(cm.MINT_POLICY_SEED))
    assert isinstance(val, MaryValue)
    assert val.asset_map() == {(pid, cm.MINT_ASSET): 1_000}
    n_byron_txs = sum(
        1 for s in range(1, 40) if s % cfg.byron_epoch_length != 0
    )
    assert int(val) == cm.LEDGER_GENESIS_COIN - n_byron_txs * cm.LEDGER_BYRON_FEE


def test_cardano_analyser_cli(tmp_path, capsys):
    """db_analyser --cardano: the CLI drives the composite revalidation
    (DBAnalyser/Block/Cardano.hs block dispatch analog)."""
    import json

    from ouroboros_consensus_tpu.tools import db_analyser

    path = str(tmp_path / "db")
    cfg = composite.CardanoMockConfig()  # CLI defaults
    n = composite.synthesize(path, cfg, 2 * 40 + 2 * 60 + 30)
    db_analyser.main([
        "--db", path, "--cardano", "--backend", "host",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["error"] is None and out["valid"] == out["blocks"] == n
    assert set(out["per_era"]) == {"byron", "shelley", "babbage"}


def test_cardano_cli_pipeline(tmp_path, capsys):
    """tools-test shape (test/tools-test/Main.hs): db_synthesizer
    --cardano forges the composite from the CLI, db_analyser --cardano
    revalidates it — with the real era ledgers in both."""
    import json

    from ouroboros_consensus_tpu.tools import db_analyser, db_synthesizer

    path = str(tmp_path / "db")
    db_synthesizer.main([
        "--out", path, "--cardano", "--with-ledgers", "--slots", "230",
    ])
    forged = capsys.readouterr().out
    assert "forged" in forged
    db_analyser.main([
        "--db", path, "--cardano", "--with-ledgers", "--backend", "host",
    ])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["error"] is None and out["valid"] == out["blocks"] > 0
    assert set(out["per_era"]) == {"byron", "shelley", "babbage"}


@pytest.mark.skipif(
    not __import__("os").environ.get("OCT_SLOW_TESTS"),
    reason="fused-kernel compile on XLA:CPU; set OCT_SLOW_TESTS=1",
)
def test_sharded_backend_through_composite(chain):
    """Config 5 over the multi-chip SPMD backend: the Praos-class era
    segments shard over the 8-device virtual mesh (the PBFT segment
    stays a batched Ed25519 verify), agreeing with the host fold."""
    path, n = chain
    res = composite.revalidate(path, CFG, backend="sharded")
    assert res.error is None, repr(res.error)
    assert res.n_valid == n
    host = composite.revalidate(path, CFG, backend="host")
    assert res.final_state == host.final_state
