"""octflow tier-1 gate (Pass 6): exception-routing & degradation-
lattice checkers.

Four layers, mirroring test_concurrency.py:
  1. fixture coverage — every FLOW rule fires on its purpose-built
     positive at the EXACT pinned (file, line) and honors its
     suppressed twin (tests/lint_fixtures/flow_*.py);
  2. the tree gate — zero unsuppressed findings over the shipped
     default roots, and the flow.json ratchet round-trips clean;
  3. the wiring — scripts/lint.py exits 8 on a seeded violation and
     maps --changed diffs onto the sweep; the `flow` subcommand's
     sorted-keys --json is byte-stable and exits 8 on its own;
  4. the routing the analyzer certifies — node/exit.triage()'s
     DISPOSITIONS table (one assertion per taxonomy row) and
     TPraosProtocol.recover_fold's degradation floor (the FLOW304
     remediation: a RECOVER-class device fault lands on _host_fold,
     everything else surfaces raw).

The kill-switch drift gate (analysis/envlevers.check_kill_switches)
rides along: the obs/README.md `=0` rows must match the FLOW305 lever
inventory pinned in analysis/flow.json in both directions.
"""

import importlib.util
import json
import os

import pytest

from ouroboros_consensus_tpu.analysis import envlevers, flow
from ouroboros_consensus_tpu.analysis.__main__ import main as analysis_cli

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_fixtures")


def _load_lint():
    spec = importlib.util.spec_from_file_location(
        "lint_gate_flow", os.path.join(REPO, "scripts", "lint.py")
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    return lint


def _cfg(**over):
    """A self-contained flow_roots table for fixture sweeps: everything
    in raise scope, no ladder/levers/pins unless the fixture opts in."""
    base = {
        "raise_scope": [""],
        "dispositions_table": "DISPOSITIONS",
        "builtin_exempt": ["ValueError", "TypeError"],
        "ladder": {"module": "", "table": "LADDERS", "router": "",
                   "terminal": "", "roots": []},
        "verdict_roots": [],
        "dispatch": {"functions": [], "protectors": [], "exclude": []},
        "kill_switches": [],
        "sanctioned_broad": [],
        "redispatch_pins": {},
    }
    base.update(over)
    return base


_FIXTURE_CFGS = {
    "flow_raise": _cfg(),
    "flow_launder": _cfg(ladder={
        "module": "", "table": "LADDERS", "router": "", "terminal": "",
        "roots": ["recover_window", "recover_window_triaged",
                  "recover_window_suppressed"],
    }),
    "flow_verdict": _cfg(verdict_roots=[
        "validate_chain", "validate_chain_forwarding",
        "validate_chain_suppressed",
    ]),
    "flow_lattice": _cfg(
        ladder={"module": "flow_lattice", "table": "LADDERS",
                "router": "RecoverySupervisor._run_rung",
                "terminal": "host_reference_fold", "roots": []},
        dispatch={"functions": ["run_batch"],
                  "protectors": ["recover_window"], "exclude": []},
    ),
    "flow_levers": _cfg(kill_switches=[
        "OCT_FX_DEAD", "OCT_FX_DEAD_SUPP", "OCT_FX_GOOD",
        "OCT_FX_REENTER",
    ]),
    "flow_broad": _cfg(sanctioned_broad=["pump"]),
    "flow_redispatch": _cfg(redispatch_pins={
        "flow_redispatch.materialize": ["reference_fold"],
        "flow_redispatch.routed": ["reference_fold"],
        "flow_redispatch.drifted_suppressed": ["reference_fold"],
        "flow_redispatch.gone_fn": ["reference_fold"],
    }),
    "flow_stale": _cfg(),
}


def _sweep_fixture(name):
    rep = flow.sweep_paths(
        [os.path.join(FIXTURES, f"{name}.py")], rel_to=FIXTURES,
        roots_table=_FIXTURE_CFGS[name],
    )
    return rep.findings


# ---------------------------------------------------------------------------
# 1 — fixtures: exact (rule, line) pins per seeded violation
# ---------------------------------------------------------------------------

# (fixture module, unsuppressed (rule, line) pins, suppressed pins)
_FIXTURE_PINS = [
    ("flow_raise", [("FLOW301", 31)], [("FLOW301", 51)]),
    ("flow_launder", [("FLOW302", 32)], [("FLOW302", 48)]),
    ("flow_verdict", [("FLOW303", 17)], [("FLOW303", 32)]),
    ("flow_lattice",
     [("FLOW304", 24), ("FLOW304", 36)], [("FLOW304", 47)]),
    ("flow_levers",
     [("FLOW305", 9), ("FLOW305", 28)], [("FLOW305", 10)]),
    ("flow_broad",
     [("FLOW306", 10), ("FLOW306", 17)], [("FLOW306", 38)]),
    ("flow_redispatch",
     [("FLOW307", 0), ("FLOW307", 12)], [("FLOW307", 20)]),
    ("flow_stale", [("FLOW308", 8)], []),
]


@pytest.mark.parametrize(
    "name,fired,suppressed", _FIXTURE_PINS,
    ids=[p[0] for p in _FIXTURE_PINS],
)
def test_fixture_exact_findings(name, fired, suppressed):
    """Set equality, not subset: a fixture firing anything beyond its
    pins means a checker regressed into noise."""
    found = _sweep_fixture(name)
    assert {(f.rule, f.line) for f in found if not f.suppressed} \
        == set(fired)
    assert {(f.rule, f.line) for f in found if f.suppressed} \
        == set(suppressed)


def test_every_flow_rule_represented():
    all_rules = {r for _, fired, _ in _FIXTURE_PINS for r, _ in fired}
    assert all_rules == set(flow.RULES)


def test_suppressed_twin_for_every_suppressible_rule():
    # FLOW308 is the suppression audit itself — the one rule without a
    # suppressed twin in the fixture set
    twinned = {r for _, _, sup in _FIXTURE_PINS for r, _ in sup}
    assert twinned == set(flow.RULES) - {"FLOW308"}


def test_lattice_reports_both_hole_kinds():
    msgs = [f.message for f in _sweep_fixture("flow_lattice")
            if f.line == 24]
    # line 24 carries BOTH wellformedness holes: the unrouted ghost
    # rung and the floorless chain missing its terminal
    assert any("missing-rung" in m and "no branch" in m for m in msgs)
    assert any("floorless" in m and "host_reference_fold" in m
               for m in msgs)


def test_levers_reports_dead_and_reentry():
    by_line = {f.line: f.message for f in _sweep_fixture("flow_levers")
               if not f.suppressed}
    assert "OCT_FX_DEAD" in by_line[9] and "dead lever" in by_line[9]
    assert "OCT_FX_REENTER" in by_line[28] \
        and "identical callees" in by_line[28]


def test_redispatch_missing_function_vs_missing_callee():
    found = [f for f in _sweep_fixture("flow_redispatch")
             if not f.suppressed]
    by_line = {f.line: f.message for f in found}
    assert "gone_fn" in by_line[0] and "no longer exists" in by_line[0]
    assert "reference_fold" in by_line[12]


def test_standalone_comment_does_not_suppress():
    src = (
        "def f(fn):\n"
        "    try:\n"
        "        return fn()\n"
        "    # octflow: disable=FLOW306\n"
        "    except BaseException:\n"
        "        return None\n"
    )
    found = flow.sweep_source(src, "scopes", roots_table=_cfg())
    by_rule = {f.rule: f for f in found}
    # the comment line above the handler suppresses nothing — the
    # grammar is line-exact (finding line or def line only) — so the
    # finding fires AND the comment is audited as stale
    assert not by_rule["FLOW306"].suppressed
    assert by_rule["FLOW308"].line == 4


def test_def_line_suppression_scopes_whole_function():
    src = (
        "def f(fn):  # octflow: disable=FLOW306\n"
        "    try:\n"
        "        return fn()\n"
        "    except BaseException:\n"
        "        return None\n"
    )
    found = flow.sweep_source(src, "scopes", roots_table=_cfg())
    assert [f.rule for f in found] == ["FLOW306"]
    assert found[0].suppressed


# ---------------------------------------------------------------------------
# 2 — the tree gate + ratchet round-trip
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tree_report():
    return flow.sweep_paths(flow.default_roots(REPO), REPO)


def test_tree_has_no_unsuppressed_findings(tree_report):
    bad = [f.format() for f in tree_report.findings if not f.suppressed]
    assert not bad, "\n".join(bad)


def test_every_kill_switch_guards_something(tree_report):
    # the FLOW305 analysis proved every documented `=0` lever gates at
    # least one branch — a zero here is a dead lever the rule missed
    for entry in tree_report.inventory["levers"]:
        name, guards = entry.split(":guards=")
        assert int(guards) > 0, f"{name} pinned with zero guard sites"


def test_ratchet_round_trips_clean(tree_report):
    violations, stale = flow.check_flow(tree_report, flow.load_baseline())
    assert violations == []
    assert stale == []


def test_shipped_baseline_matches_payload(tree_report):
    payload = flow.baseline_payload(tree_report)
    shipped = flow.load_baseline()
    assert payload["findings"] == shipped["findings"] == []
    assert payload["inventory"] == shipped["inventory"]


def test_inventory_drift_is_a_violation(tree_report):
    base = json.loads(json.dumps(flow.load_baseline()))
    base["inventory"]["handlers"] = base["inventory"]["handlers"][:-1]
    violations, _ = flow.check_flow(tree_report, base)
    assert any("inventory drift in `handlers`" in v for v in violations)


def test_new_finding_is_a_violation_and_keys_are_line_free():
    found = _sweep_fixture("flow_broad")
    rep = flow.FlowReport(found, flow.load_baseline().get("inventory", {}))
    violations, _ = flow.check_flow(rep, flow.load_baseline())
    assert any("FLOW306" in v and "bare_fires" in v for v in violations)
    # ratchet keys carry rule::path::message, never line numbers — a
    # pure-whitespace shift above a grandfathered finding cannot
    # resurrect it
    for f in found:
        assert f"::{f.line}" not in f.key()


# ---------------------------------------------------------------------------
# 3 — wiring: lint.py exit 8, --changed mapping, flow subcommand
# ---------------------------------------------------------------------------


def test_lint_changed_maps_failure_plane_to_sweep():
    lint = _load_lint()
    assert lint._flow_selected({"ouroboros_consensus_tpu/node/exit.py"})
    assert lint._flow_selected({"ouroboros_consensus_tpu/obs/recovery.py"})
    assert lint._flow_selected({"ouroboros_consensus_tpu/protocol/batch.py"})
    assert lint._flow_selected({"ouroboros_consensus_tpu/protocol/tpraos.py"})
    assert lint._flow_selected({"ouroboros_consensus_tpu/storage/repair.py"})
    assert lint._flow_selected({"ouroboros_consensus_tpu/testing/chaos.py"})
    assert lint._flow_selected({"ouroboros_consensus_tpu/analysis/flow_roots.json"})
    assert not lint._flow_selected({"README.md"})
    assert not lint._flow_selected({"ouroboros_consensus_tpu/ops/pk/msm.py"})
    # empty diff / no git -> conservative full sweep
    assert lint._flow_selected(set())


def test_lint_exits_8_on_seeded_violation(monkeypatch, capsys):
    """End to end through scripts/lint.py main(): poison the octflow
    roots with the FLOW302 corruption-laundering fixture (the PR 13
    bug shape), assert the NEW exit code, and assert --changed on an
    unrelated diff skips the sweep entirely. Driven through --changed
    so the sync/octlint passes stay scoped to one file — the full-run
    selection logic (`not args.changed` -> sweep) is pinned by
    test_lint_changed_maps_failure_plane_to_sweep's empty-diff case."""
    lint = _load_lint()
    seeded = [os.path.join(FIXTURES, "flow_launder.py")]
    monkeypatch.setattr(flow, "default_roots", lambda repo=None: seeded)
    monkeypatch.setattr(
        flow, "load_roots", lambda: _FIXTURE_CFGS["flow_launder"])
    # an unrelated --changed diff skips the sweep: exit 0 even with
    # the poisoned roots
    monkeypatch.setattr(lint, "_changed_files", lambda: {"README.md"})
    assert lint.main(["--no-graphs", "--changed"]) == 0
    capsys.readouterr()
    # a failure-plane diff selects it: the laundering handler fails
    # the gate with the Pass-6 exit code
    monkeypatch.setattr(
        lint, "_changed_files",
        lambda: {"ouroboros_consensus_tpu/node/exit.py"},
    )
    assert lint.main(["--no-graphs", "--changed"]) == 8
    assert "FLOW302" in capsys.readouterr().out


def test_flow_subcommand_exit_and_json_byte_stable(capsys):
    fixture = os.path.join(FIXTURES, "flow_stale.py")
    # findings not in the shipped ratchet -> the distinct exit code
    assert analysis_cli(["flow", "--paths", fixture]) == 8
    capsys.readouterr()
    # --no-ratchet reports without enforcing
    assert analysis_cli(["flow", "--paths", fixture, "--no-ratchet"]) == 0
    capsys.readouterr()
    assert analysis_cli(
        ["flow", "--paths", fixture, "--no-ratchet", "--json"]
    ) == 0
    first = capsys.readouterr().out
    assert analysis_cli(
        ["flow", "--paths", fixture, "--no-ratchet", "--json"]
    ) == 0
    second = capsys.readouterr().out
    assert first == second  # byte-stable for CI diffing
    doc = json.loads(first)
    assert doc["ok"] is True
    assert [(f["rule"], f["line"]) for f in doc["findings"]] \
        == [("FLOW308", 8)]


def test_flow_subcommand_clean_tree_exits_0(tree_report, monkeypatch,
                                            capsys):
    # reuse the module fixture's whole-tree sweep (the sweep itself is
    # pinned by the tree-gate layer above) and drive the subcommand's
    # ratchet check + JSON emit + exit-code logic over the real report
    monkeypatch.setattr(flow, "sweep_paths",
                        lambda *a, **k: tree_report)
    assert analysis_cli(["flow", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True and doc["findings"] == []
    assert doc["inventory"] == flow.load_baseline()["inventory"]


# ---------------------------------------------------------------------------
# kill-switch drift gate (analysis/envlevers.check_kill_switches)
# ---------------------------------------------------------------------------


def test_kill_switch_rows_match_pinned_inventory():
    violations = envlevers.check_kill_switches()
    assert not violations, "\n".join(violations)


def test_kill_switch_gate_catches_both_directions(tmp_path):
    readme = tmp_path / "README.md"
    readme.write_text(
        "## Levers\n\n"
        "| Env | Effect |\n|---|---|\n"
        "| `OCT_FAKE_KILL=0` | documented but never pinned |\n"
        "| `OCT_CHECKPOINT=<file>` | a value lever: not a kill-switch |\n"
    )
    base = {"inventory": {"levers": ["OCT_STALE_PIN:guards=3"]}}
    out = envlevers.check_kill_switches(str(readme), base)
    assert any("OCT_FAKE_KILL" in v and "no FLOW305" in v for v in out)
    assert any("OCT_STALE_PIN" in v and "stale pin" in v for v in out)
    assert not any("OCT_CHECKPOINT" in v for v in out)


def test_kill_switch_subset_of_documented_levers():
    kills = envlevers.kill_switch_levers()
    assert kills <= envlevers.documented_levers()
    # the pinned inventory and the README agree on the exact set
    pinned = {e.split(":", 1)[0]
              for e in flow.load_baseline()["inventory"]["levers"]}
    assert pinned == kills


# ---------------------------------------------------------------------------
# 4 — the routing octflow certifies: triage() + recover_fold
# ---------------------------------------------------------------------------


def test_dispositions_table_routes_every_row():
    from ouroboros_consensus_tpu.node import exit as node_exit

    D = node_exit.Disposition
    want = {
        "REFUSE": D.REFUSE, "REPAIR": D.REPAIR,
        "RECOVER": D.RECOVER, "PROPAGATE": D.PROPAGATE,
    }
    for name, dispo in node_exit.DISPOSITIONS.items():
        assert dispo in want.values(), name
    # one live-class probe per disposition, through the real triage()
    from ouroboros_consensus_tpu.protocol.praos import PraosValidationError
    from ouroboros_consensus_tpu.storage.guard import DbLocked
    from ouroboros_consensus_tpu.storage.immutable import ImmutableDBError
    from ouroboros_consensus_tpu.testing.chaos import ChaosError

    assert node_exit.triage(DbLocked("x")) is D.REFUSE
    assert node_exit.triage(ImmutableDBError("x")) is D.REPAIR
    assert node_exit.triage(ChaosError("x")) is D.RECOVER
    assert node_exit.triage(PraosValidationError("x")) is D.PROPAGATE


def test_triage_walks_the_mro():
    from ouroboros_consensus_tpu.node import exit as node_exit

    D = node_exit.Disposition

    class SubLocked(Exception):
        pass

    # a subclass of a classified type inherits the row through __mro__
    from ouroboros_consensus_tpu.storage.guard import DbLocked

    class Derived(DbLocked):
        pass

    assert node_exit.triage(Derived("x")) is D.REFUSE
    # an unclassified tree falls to PROPAGATE, never a silent default
    assert node_exit.triage(SubLocked("x")) is D.PROPAGATE


def test_triage_routes_xla_runtime_by_name():
    from ouroboros_consensus_tpu.node import exit as node_exit

    class XlaRuntimeError(Exception):  # jaxlib spells it this way
        pass

    assert node_exit.triage(XlaRuntimeError("RESOURCE_EXHAUSTED")) \
        is node_exit.Disposition.RECOVER


def _bare_tpraos():
    from ouroboros_consensus_tpu.protocol import tpraos

    return object.__new__(tpraos.TPraosProtocol)


def test_recover_fold_degrades_recover_class_to_host_fold(monkeypatch):
    from ouroboros_consensus_tpu.testing.chaos import ChaosError

    proto = _bare_tpraos()
    events = []

    def boom(backend, ticked, hvs, collect_states):
        raise ChaosError("injected device fault")

    proto._device_batch = boom
    proto._host_fold = lambda ticked, hvs, collect: ("host", hvs)
    from ouroboros_consensus_tpu.obs import recovery
    monkeypatch.setattr(
        recovery, "note_recovery_event",
        lambda *a, **k: events.append(a[0]),
    )
    out = proto.recover_fold("native", None, [1, 2], False)
    assert out == ("host", [1, 2])
    assert events == ["host-fold", "recovered"]


def test_recover_fold_surfaces_propagate_class(monkeypatch):
    from ouroboros_consensus_tpu.protocol.praos import PraosValidationError

    proto = _bare_tpraos()

    def boom(backend, ticked, hvs, collect_states):
        raise PraosValidationError("wrong, not broken")

    proto._device_batch = boom
    proto._host_fold = lambda *a: pytest.fail(
        "PROPAGATE-class fault must never reach the host fold")
    with pytest.raises(PraosValidationError):
        proto.recover_fold("native", None, [1], False)


def test_recover_fold_respects_the_kill_switch(monkeypatch):
    from ouroboros_consensus_tpu.testing.chaos import ChaosError

    proto = _bare_tpraos()

    def boom(backend, ticked, hvs, collect_states):
        raise ChaosError("injected device fault")

    proto._device_batch = boom
    proto._host_fold = lambda *a: pytest.fail(
        "OCT_RECOVERY=0 must restore raise-through")
    monkeypatch.setenv("OCT_RECOVERY", "0")
    with pytest.raises(ChaosError):
        proto.recover_fold("native", None, [1], False)
