"""Host reference crypto: Ed25519 (RFC 8032 vectors), ECVRF, KES, CBOR."""

import hashlib
import os

import pytest

from ouroboros_consensus_tpu.ops.host import ecvrf, ed25519, hashes, kes
from ouroboros_consensus_tpu.utils import cbor

# --- Ed25519 RFC 8032 test vectors (section 7.1) ---------------------------

RFC8032_VECTORS = [
    # (secret, public, message, signature)
    (
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60",
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a",
        "",
        "e5564300c360ac729086e2cc806e828a84877f1eb8e5d974d873e06522490155"
        "5fb8821590a33bacc61e39701cf9b46bd25bf5f0595bbe24655141438e7a100b",
    ),
    (
        "4ccd089b28ff96da9db6c346ec114e0f5b8a319f35aba624da8cf6ed4fb8a6fb",
        "3d4017c3e843895a92b70aa74d1b7ebc9c982ccf2ec4968cc0cd55f12af4660c",
        "72",
        "92a009a9f0d4cab8720e820b5f642540a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c387b2eaeb4302aeeb00d291612bb0c00",
    ),
    (
        "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7",
        "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025",
        "af82",
        "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
        "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a",
    ),
]


@pytest.mark.parametrize("sk,pk,msg,sig", RFC8032_VECTORS)
def test_ed25519_rfc8032(sk, pk, msg, sig):
    seed = bytes.fromhex(sk)
    public = bytes.fromhex(pk)
    message = bytes.fromhex(msg)
    signature = bytes.fromhex(sig)
    assert ed25519.secret_to_public(seed) == public
    assert ed25519.sign(seed, message) == signature
    assert ed25519.verify(public, message, signature)


def test_ed25519_reject_tampered():
    seed = os.urandom(32)
    pk = ed25519.secret_to_public(seed)
    msg = b"ouroboros"
    sig = ed25519.sign(seed, msg)
    assert ed25519.verify(pk, msg, sig)
    bad = bytearray(sig)
    bad[0] ^= 1
    assert not ed25519.verify(pk, msg, bytes(bad))
    assert not ed25519.verify(pk, msg + b"x", sig)
    # non-canonical s >= L rejected
    s = int.from_bytes(sig[32:], "little") + ed25519.L
    if s < 1 << 256:
        bad2 = sig[:32] + s.to_bytes(32, "little")
        assert not ed25519.verify(pk, msg, bad2)


def test_point_roundtrip_and_curve_membership():
    for i in [1, 2, 7, 12345, ed25519.L - 1]:
        pt = ed25519.point_mul(i, ed25519.B)
        assert ed25519.point_is_on_curve(pt)
        enc = ed25519.point_compress(pt)
        dec = ed25519.point_decompress(enc)
        assert dec is not None
        assert ed25519.point_equal(pt, dec)


# --- ECVRF ------------------------------------------------------------------


def test_ecvrf_prove_verify_roundtrip():
    seed = bytes(range(32))
    pk = ed25519.secret_to_public(seed)
    for alpha in [b"", b"slot-42", os.urandom(100)]:
        pi = ecvrf.prove(seed, alpha)
        assert len(pi) == ecvrf.PROOF_BYTES
        beta = ecvrf.verify(pk, pi, alpha)
        assert beta is not None and len(beta) == ecvrf.OUTPUT_BYTES
        assert beta == ecvrf.proof_to_hash(pi)


def test_ecvrf_deterministic():
    seed = b"\x07" * 32
    assert ecvrf.prove(seed, b"a") == ecvrf.prove(seed, b"a")
    assert ecvrf.prove(seed, b"a") != ecvrf.prove(seed, b"b")


def test_ecvrf_reject_bad():
    seed = os.urandom(32)
    pk = ed25519.secret_to_public(seed)
    alpha = b"input"
    pi = ecvrf.prove(seed, alpha)
    assert ecvrf.verify(pk, pi, alpha + b"!") is None
    bad = bytearray(pi)
    bad[40] ^= 1  # corrupt c
    assert ecvrf.verify(pk, bytes(bad), alpha) is None
    other_pk = ed25519.secret_to_public(os.urandom(32))
    assert ecvrf.verify(other_pk, pi, alpha) is None


def test_elligator_output_on_curve():
    for i in range(8):
        h = ecvrf.hash_to_curve(b"\x01" * 32, bytes([i]))
        assert ed25519.point_is_on_curve(h)
        # cofactor-cleared => in prime-order subgroup: L*H == identity
        assert ed25519.point_equal(
            ed25519.point_mul(ed25519.L, h), ed25519.IDENT
        )


# --- KES --------------------------------------------------------------------


def test_kes_sign_verify_all_periods_depth3():
    seed = b"\x42" * 32
    depth = 3
    vk = kes.derive_vk(seed, depth)
    for t in range(1 << depth):
        sig = kes.sign(seed, depth, t, b"header-body")
        assert len(sig) == kes.sig_bytes(depth)
        assert kes.verify(vk, depth, t, b"header-body", sig)
        assert not kes.verify(vk, depth, t, b"tampered", sig)
        # wrong period fails (different leaf key)
        assert not kes.verify(vk, depth, (t + 1) % (1 << depth), b"header-body", sig)


def test_kes_depth7_spot():
    seed = os.urandom(32)
    depth = 7
    vk = kes.derive_vk(seed, depth)
    for t in [0, 1, 63, 64, 127]:
        sig = kes.sign(seed, depth, t, b"m")
        assert kes.verify(vk, depth, t, b"m", sig)
    bad_vk = hashlib.blake2b(b"x", digest_size=32).digest()
    assert not kes.verify(bad_vk, depth, 0, b"m", kes.sign(seed, depth, 0, b"m"))


# --- hashes / nonce helpers -------------------------------------------------


def test_hash_helpers():
    assert len(hashes.blake2b_256(b"")) == 32
    assert len(hashes.blake2b_224(b"")) == 28
    assert hashes.input_vrf(5, b"\x00" * 32) != hashes.input_vrf(6, b"\x00" * 32)
    beta = b"\xaa" * 64
    assert 0 <= hashes.vrf_leader_value(beta) < 1 << 256
    assert len(hashes.vrf_nonce_value(beta)) == 32
    n1 = hashes.nonce_combine(b"\x01" * 32, b"\x02" * 32)
    assert len(n1) == 32


# --- CBOR -------------------------------------------------------------------


def test_cbor_roundtrip():
    cases = [
        0,
        23,
        24,
        255,
        256,
        2**32,
        2**63,
        -1,
        -25,
        -(2**40),
        b"",
        b"\x00\x01\x02",
        "hello",
        [],
        [1, [2, 3], b"x"],
        {1: b"a", b"k": [True, False, None]},
        cbor.Tag(24, b"\x82\x01\x02"),
        True,
        False,
        None,
    ]
    for c in cases:
        assert cbor.decode(cbor.encode(c)) == c


def test_cbor_canonical_known_bytes():
    assert cbor.encode(0) == b"\x00"
    assert cbor.encode(23) == b"\x17"
    assert cbor.encode(24) == b"\x18\x18"
    assert cbor.encode([1, 2, 3]) == b"\x83\x01\x02\x03"
    assert cbor.encode(b"\x01\x02") == b"\x42\x01\x02"
    assert cbor.encode("a") == b"\x61\x61"
    assert cbor.encode(-1) == b"\x20"


def test_cbor_decode_prefix():
    data = cbor.encode([1, 2]) + cbor.encode(b"tail")
    v, off = cbor.decode_prefix(data, 0)
    assert v == [1, 2]
    v2, off2 = cbor.decode_prefix(data, off)
    assert v2 == b"tail" and off2 == len(data)


def test_cbor_float_and_simple_decode():
    # floats whose bit patterns collide with simple-value codes
    import struct
    for v in [0.0, 1.5, -2.25, struct.unpack(">d", (20).to_bytes(8, "big"))[0]]:
        assert cbor.decode(cbor.encode(v)) == v
    # half/single width floats decode too
    assert cbor.decode(b"\xf9\x3c\x00") == 1.0
    assert cbor.decode(b"\xfa\x3f\x80\x00\x00") == 1.0
    with pytest.raises(cbor.DecodeError):
        cbor.decode(b"\xf8\x20")  # unsupported simple value
