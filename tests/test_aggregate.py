"""Aggregated (RLC + MSM) window verification — dispatch plumbing and
full differentials.

Fast tier: the aggregate DISPATCH path with a stubbed aggregate core —
clean windows ride the bitmask fast path end to end, a nonzero
aggregate re-dispatches the per-lane packed program and the result is
byte-identical to the sequential fold (the crypto itself is stubbed
hash-only, PR-2 pattern, so the default tier never pays the XLA:CPU
curve compile).

Slow tier: the REAL thing on CPU — the bench-chain shape validated
through the aggregated path vs the per-lane path (OCT_VRF_AGG=0) vs the
host sequential fold, byte-identical on clean chains; and the
corrupted-lane matrix (ocert / kes / vrf proof / beta) where the
poisoned aggregate must fall back and isolate exactly the bad lane with
the exact reference error. Plus the 256-bit MSM differential.
"""

import os
import random
from dataclasses import replace
from fractions import Fraction

import numpy as np
import pytest

import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu.block.forge import forge_block
from ouroboros_consensus_tpu.ops import blake2b
from ouroboros_consensus_tpu.protocol import batch as pbatch
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.protocol.praos import PraosIsLeader
from ouroboros_consensus_tpu.testing import fixtures


def make_params(kes_depth=3, epoch_length=100_000):
    return praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=epoch_length,
        kes_depth=kes_depth,
    )


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(50 + i, kes_depth=3) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


def real_chain(params, pools, lview, n, tamper=None, first_slot=100,
               vrf_batch=None):
    """Real-codec batch-compatible chain forged on WINNING slots only
    (the leader lottery is consulted per slot, db-synthesizer style, so
    a clean chain validates end to end); `tamper(i, pool, is_leader,
    ocert) -> (is_leader, ocert, kes_flip)` lets a lane be corrupted
    BEFORE the body is built, so the window still qualifies for packed
    staging (the corruption is inside the signed body, exactly like a
    forged-on-chain attack). `vrf_batch(i) -> bool` selects the proof
    format per header (True = 128-byte batch-compatible, False =
    80-byte draft-03) so mixed-format chains stay real-codec."""
    from ouroboros_consensus_tpu.block.forge import evaluate_vrf
    from ouroboros_consensus_tpu.protocol import nonces as nonces_mod
    from ouroboros_consensus_tpu.protocol.leader import check_leader_value

    nonce = b"\x07" * 32
    hvs, prev = [], b"\xaa" * 32
    slot = first_slot
    prev_fmt = os.environ.get("OCT_VRF_BATCH")
    while len(hvs) < n:
        if vrf_batch is not None:
            os.environ["OCT_VRF_BATCH"] = "1" if vrf_batch(len(hvs)) else "0"
        winner = None
        for pool in pools:
            cand = evaluate_vrf(pool, slot, nonce)
            stake = lview.pool_distr[pool.pool_id].stake
            if check_leader_value(
                nonces_mod.vrf_leader_value(cand.vrf_output), stake,
                params.active_slot_coeff,
            ):
                winner, is_leader = pool, cand
                break
        if winner is None:
            slot += 1
            continue
        i = len(hvs)
        kp = params.kes_period_of(slot)
        c0 = max(0, kp - (kp % params.max_kes_evolutions))
        ocert = winner.make_ocert(0, c0)
        kes_flip = False
        if tamper is not None:
            is_leader, ocert, kes_flip = tamper(i, winner, is_leader, ocert)
        blk = _forge_raw(
            params, winner, slot, 30 + i, prev, nonce, (b"tx-%d" % i,),
            is_leader, ocert,
        )
        hv = blk.header.to_view()
        if kes_flip:
            if callable(kes_flip):
                hv = replace(hv, kes_sig=kes_flip(hv.kes_sig))
            else:
                sig = bytearray(hv.kes_sig)
                sig[1] ^= 1
                hv = replace(hv, kes_sig=bytes(sig))
        hvs.append(hv)
        prev = blk.header.hash_
        slot += 1
    if vrf_batch is not None:
        if prev_fmt is None:
            os.environ.pop("OCT_VRF_BATCH", None)
        else:
            os.environ["OCT_VRF_BATCH"] = prev_fmt
    return nonce, hvs


def _forge_raw(params, pool, slot, block_no, prev, nonce, txs, is_leader,
               ocert):
    """forge_block with an explicit (possibly tampered) OCert but the
    synthesizer-style static KES signing."""
    from ouroboros_consensus_tpu.block.praos_block import (
        Block, Header, HeaderBody, body_hash,
    )
    from ouroboros_consensus_tpu.ops.host import kes as host_kes

    kp = params.kes_period_of(slot)
    body = HeaderBody(
        block_no=block_no, slot=slot, prev_hash=prev,
        issuer_vk=pool.vk_cold, vrf_vk=pool.vrf_vk,
        vrf_output=is_leader.vrf_output, vrf_proof=is_leader.vrf_proof,
        body_size=sum(len(t) for t in txs), body_hash=body_hash(txs),
        ocert=ocert, protocol_version=(9, 0),
    )
    t = kp - ocert.kes_period
    kes_sig = host_kes.sign(pool.kes_seed, pool.kes_depth, t,
                            body.signed_bytes)
    return Block(Header(body, kes_sig), tuple(txs))


def host_fold(params, lview, nonce, hvs):
    """The sequential reference: (n_valid, error-or-None, final state)."""
    st = replace(praos.PraosState(), epoch_nonce=nonce)
    for i, hv in enumerate(hvs):
        ticked = praos.tick(params, lview, hv.slot, st)
        try:
            st = praos.update(params, hv, hv.slot, ticked)
        except praos.PraosValidationError as e:
            return i, e, st
    return len(hvs), None, st


def _results_match_host(res, params, lview, nonce, hvs):
    n, err, st = host_fold(params, lview, nonce, hvs)
    assert res.n_valid == n, (res.n_valid, n, repr(res.error))
    assert (res.error is None) == (err is None), (res.error, err)
    if err is not None:
        assert type(res.error) is type(err), (res.error, err)
        assert vars(res.error) == vars(err)
    else:
        assert res.state == st


# ---------------------------------------------------------------------------
# Fast tier: dispatch plumbing with a stubbed aggregate core
# ---------------------------------------------------------------------------


def _hash_tail(beta_decl_bt):
    bd = jnp.asarray(beta_decl_bt).astype(jnp.int32)
    b = bd.shape[0]
    tag_l = jnp.broadcast_to(jnp.asarray([ord("L")], jnp.int32), (b, 1))
    lv = blake2b.blake2b_fixed(jnp.concatenate([tag_l, bd], axis=-1), 65, 32)
    tag_n = jnp.broadcast_to(jnp.asarray([ord("N")], jnp.int32), (b, 1))
    eta1 = blake2b.blake2b_fixed(jnp.concatenate([tag_n, bd], axis=-1), 65, 32)
    eta = blake2b.blake2b_fixed(eta1, 32, 32)
    return eta, lv


def _stub_aggregate(agg_ok: bool):
    """aggregate_window stand-in: real eta/leader hashes (the fold must
    stay byte-exact), all-pass cheap checks, forced aggregate verdict."""
    from ouroboros_consensus_tpu.ops.pk import aggregate as agg_mod

    def fn(*limb, kes_depth):
        beta_decl = limb[-3]  # [64, T] limb-first
        eta, lv = _hash_tail(jnp.transpose(beta_decl))
        eta, lv = jnp.transpose(eta), jnp.transpose(lv)
        t = beta_decl.shape[-1]
        ok = jnp.full((t,), bool(agg_ok))
        flags = jnp.stack([
            ok.astype(jnp.int32), ok.astype(jnp.int32),
            ok.astype(jnp.int32),
            jnp.ones((t,), jnp.int32), jnp.zeros((t,), jnp.int32),
        ])
        return agg_mod.AggregateVerdicts(
            flags, eta, lv, jnp.asarray(bool(agg_ok)),
            jnp.asarray(bool(agg_ok)),
        )

    return fn


@pytest.fixture
def fenced_jits(monkeypatch):
    before = set(pbatch._JIT)
    yield
    for k in set(pbatch._JIT) - before:
        del pbatch._JIT[k]


@pytest.mark.parametrize("clean", [True, False])
def test_agg_dispatch_clean_vs_fallback(pools, lview, clean, monkeypatch,
                                        fenced_jits):
    """Clean windows ride the aggregate bitmask fast path; a nonzero
    aggregate re-dispatches the per-lane packed program (stubbed
    hash-only here) and the chain result still equals the fold."""
    from ouroboros_consensus_tpu.ops.pk import aggregate as agg_mod

    params = make_params()
    nonce, hvs = real_chain(params, pools, lview, 12)
    assert len(hvs[0].vrf_proof) == 128
    monkeypatch.setattr(agg_mod, "aggregate_window", _stub_aggregate(clean))

    calls = {"fallback": 0}
    orig_xla = pbatch._jitted_packed_xla

    def counting_xla(layout, scan):
        calls["fallback"] += 1
        return orig_xla(layout, scan)

    monkeypatch.setattr(pbatch, "_jitted_packed_xla", counting_xla)
    # the per-lane fallback would compile real crypto: stub it too
    monkeypatch.setattr(pbatch, "verify_praos_any",
                        lambda *cols: _stub_verdicts(cols))

    st0 = replace(praos.PraosState(), epoch_nonce=nonce)
    res = pbatch.validate_chain(
        params, lambda _e: lview, st0, hvs, max_batch=len(hvs)
    )
    assert res.error is None and res.n_valid == len(hvs)
    # byte-exact state against the reupdate fold
    st = st0
    for hv in hvs:
        ticked = praos.tick(params, lview, hv.slot, st)
        st = praos.reupdate(params, hv, hv.slot, ticked)
    assert res.state == st
    assert calls["fallback"] == (0 if clean else 1)


def _stub_verdicts(cols):
    beta_decl = cols[-3]
    eta, lv = _hash_tail(beta_decl)
    b = jnp.asarray(beta_decl).shape[0]
    ones = jnp.ones((b,), bool)
    return pbatch.Verdicts(ones, ones, ones, ones,
                           jnp.zeros((b,), bool), eta, lv)


# ---------------------------------------------------------------------------
# Slow tier: the real aggregated crypto, differentially
# ---------------------------------------------------------------------------


def _validate(params, lview, nonce, hvs, agg: bool, monkeypatch):
    monkeypatch.setenv("OCT_VRF_AGG", "1" if agg else "0")
    st0 = replace(praos.PraosState(), epoch_nonce=nonce)
    return pbatch.validate_chain(
        params, lambda _e: lview, st0, hvs, max_batch=len(hvs)
    )


@pytest.mark.slow
def test_aggregate_clean_chain_matches_per_lane_and_host(
    pools, lview, monkeypatch
):
    """Acceptance: aggregated window verification produces verdicts
    byte-identical to the per-lane path on a clean bench-shaped chain,
    and both equal the host sequential fold."""
    params = make_params()
    nonce, hvs = real_chain(params, pools, lview, 16)
    res_agg = _validate(params, lview, nonce, hvs, True, monkeypatch)
    res_lane = _validate(params, lview, nonce, hvs, False, monkeypatch)
    _results_match_host(res_agg, params, lview, nonce, hvs)
    _results_match_host(res_lane, params, lview, nonce, hvs)
    assert res_agg.n_valid == res_lane.n_valid
    assert res_agg.state == res_lane.state


def _torsion8():
    """A point of EXACT order 8 (host representation): [L]Q for the
    first decompressable encoding Q whose torsion component has full
    order. Adding it to a wire point encoding keeps the encoding
    canonical but moves the point off the prime-order subgroup."""
    from ouroboros_consensus_tpu.ops.host import ed25519 as he

    for b0 in range(256):
        q = he.point_decompress(bytes([b0]) + bytes(31))
        if q is None:
            continue
        t = he.point_mul(he.L, q)
        if (not he.point_equal(t, he.IDENT)
                and not he.point_equal(he.point_mul(4, t), he.IDENT)):
            return t
    raise AssertionError("no order-8 point found")


def _add_torsion(enc32: bytes) -> bytes:
    from ouroboros_consensus_tpu.ops.host import ed25519 as he

    p = he.point_decompress(enc32)
    assert p is not None
    return he.point_compress(he.point_add(p, _torsion8()))


def _tamper_factory(kind, bad_lane):
    def tamper(i, pool, is_leader, ocert):
        if i != bad_lane:
            return is_leader, ocert, False
        if kind == "ed_torsion":
            # torsion-grind the announced Ed25519 R of the OCert
            # signature: still a canonical encoding, but off the
            # prime-order subgroup — the odd (cofactor-coprime) z1
            # keeps the z1·T term alive in the aggregate, so the
            # unified identity check must reject exactly like the
            # cofactorless host reference
            sig = _add_torsion(ocert.sigma[:32]) + ocert.sigma[32:]
            return is_leader, replace(ocert, sigma=sig), False
        if kind == "kes_torsion":
            # same grind on the KES leaf signature's R (first 32 bytes
            # of the CompactSum signature) — the z2 lane of the fold
            return is_leader, ocert, (
                lambda ks: _add_torsion(ks[:32]) + ks[32:]
            )
        if kind == "ocert":
            sig = bytearray(ocert.sigma)
            sig[3] ^= 1
            return is_leader, replace(ocert, sigma=bytes(sig)), False
        if kind == "kes":
            return is_leader, ocert, True
        if kind == "vrf":
            pi = bytearray(is_leader.vrf_proof)
            pi[40] ^= 1  # announced U point
            return (PraosIsLeader(is_leader.vrf_output, bytes(pi)),
                    ocert, False)
        if kind == "beta":
            out = bytearray(is_leader.vrf_output)
            out[0] ^= 1
            return (PraosIsLeader(bytes(out), is_leader.vrf_proof),
                    ocert, False)
        raise AssertionError(kind)

    return tamper


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["ocert", "kes", "vrf", "beta"])
def test_corrupted_lane_falls_back_and_isolates(pools, lview, kind,
                                                monkeypatch):
    """Acceptance: a poisoned aggregate triggers the per-lane fallback
    and reproduces the exact reference error at exactly the bad lane —
    for each crypto family."""
    params = make_params()
    bad = 5
    nonce, hvs = real_chain(
        params, pools, lview, 9, tamper=_tamper_factory(kind, bad)
    )
    assert len(hvs[0].vrf_proof) == 128
    res = _validate(params, lview, nonce, hvs, True, monkeypatch)
    assert res.n_valid == bad
    _results_match_host(res, params, lview, nonce, hvs)
    expect = {
        "ocert": praos.InvalidSignatureOCERT,
        "kes": praos.InvalidKesSignatureOCERT,
        "vrf": praos.VRFKeyBadProof,
        "beta": praos.VRFKeyBadProof,
    }[kind]
    assert isinstance(res.error, expect), res.error


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["ed_torsion", "kes_torsion"])
def test_single_lane_torsion_grinding_rejected(pools, lview, kind,
                                               monkeypatch):
    """Round-15 regression: an adversary who grinds an 8-torsion offset
    onto a single lane's Ed25519 R (OCert sigma) or KES leaf R must be
    rejected by the UNIFIED aggregate exactly like the cofactorless
    host reference — the odd Fiat–Shamir coefficients keep the z·T
    torsion term alive in the folded identity, so the shared-bucket
    MSM cannot be talked into accepting what the per-lane path
    refuses. Same 9-lane window shape as the corruption matrix (shares
    the compiled programs)."""
    params = make_params()
    bad = 5
    nonce, hvs = real_chain(
        params, pools, lview, 9, tamper=_tamper_factory(kind, bad)
    )
    res = _validate(params, lview, nonce, hvs, True, monkeypatch)
    assert res.n_valid == bad
    _results_match_host(res, params, lview, nonce, hvs)
    expect = {
        "ed_torsion": praos.InvalidSignatureOCERT,
        "kes_torsion": praos.InvalidKesSignatureOCERT,
    }[kind]
    assert isinstance(res.error, expect), res.error


@pytest.mark.slow
@pytest.mark.parametrize("combo,first_err", [
    ((("ocert", 2), ("vrf", 6)), "ocert"),
    ((("kes", 1), ("beta", 7)), "kes"),
])
def test_multiple_dirty_stages_one_window(pools, lview, combo, first_err,
                                          monkeypatch):
    """Two DIFFERENT crypto families corrupted in the same window: the
    single aggregated identity check must go dirty, and the per-lane
    re-dispatch must reproduce the FIRST reference error at the first
    bad lane (later corruption stays behind the first-error horizon,
    exactly like the sequential fold)."""
    params = make_params()
    tampers = [_tamper_factory(kind, lane) for kind, lane in combo]

    def tamper(i, pool, is_leader, ocert):
        flip = False
        for t in tampers:
            is_leader, ocert, f = t(i, pool, is_leader, ocert)
            flip = flip or f
        return is_leader, ocert, flip

    nonce, hvs = real_chain(params, pools, lview, 9, tamper=tamper)
    res = _validate(params, lview, nonce, hvs, True, monkeypatch)
    assert res.n_valid == min(lane for _, lane in combo)
    _results_match_host(res, params, lview, nonce, hvs)
    expect = {
        "ocert": praos.InvalidSignatureOCERT,
        "kes": praos.InvalidKesSignatureOCERT,
    }[first_err]
    assert isinstance(res.error, expect), res.error


def test_mixed_format_chain_segments_before_aggregate(pools, lview,
                                                      monkeypatch,
                                                      fenced_jits):
    """A chain mixing 80-byte draft-03 and 128-byte batch-compatible
    proofs must SEGMENT at format boundaries rather than enter the
    unified one-RLC path: every window the aggregate builder sees is
    batch-compatible, draft-03 segments ride the per-lane packed
    program, and the chain result still equals the sequential
    reupdate fold (crypto stubbed hash-only — dispatch plumbing
    only)."""
    from ouroboros_consensus_tpu.ops.pk import aggregate as agg_mod

    params = make_params()
    # alternating 2-header format runs: [bc, bc][d3, d3][bc, bc][d3, d3]
    nonce, hvs = real_chain(
        params, pools, lview, 8, vrf_batch=lambda i: (i // 2) % 2 == 0
    )
    assert {len(hv.vrf_proof) for hv in hvs} == {80, 128}

    monkeypatch.setattr(agg_mod, "aggregate_window", _stub_aggregate(True))
    monkeypatch.setattr(pbatch, "verify_praos_any",
                        lambda *cols: _stub_verdicts(cols))
    seen_plens = []
    orig_agg = pbatch._jitted_packed_agg

    def counting_agg(layout, scan, mode="all"):
        seen_plens.append(layout.vrf_proof_len)
        return orig_agg(layout, scan, mode)

    monkeypatch.setattr(pbatch, "_jitted_packed_agg", counting_agg)

    st0 = replace(praos.PraosState(), epoch_nonce=nonce)
    res = pbatch.validate_chain(
        params, lambda _e: lview, st0, hvs, max_batch=len(hvs)
    )
    assert res.error is None and res.n_valid == len(hvs)
    st = st0
    for hv in hvs:
        ticked = praos.tick(params, lview, hv.slot, st)
        st = praos.reupdate(params, hv, hv.slot, ticked)
    assert res.state == st
    assert seen_plens, "no batch-compatible segment reached the aggregate"
    assert set(seen_plens) == {128}


@pytest.mark.slow
def test_msm_matches_host_256bit():
    from ouroboros_consensus_tpu.ops import bigint as bi
    from ouroboros_consensus_tpu.ops.host import ed25519 as he
    from ouroboros_consensus_tpu.ops.pk import curve as pc
    from ouroboros_consensus_tpu.ops.pk import msm

    random.seed(3)
    n = 11
    ks = [random.randrange(he.L) for _ in range(n)]
    pts = [he.point_mul(random.randrange(1, he.L), he.B) for _ in range(n)]
    acc = he.IDENT
    for k, p in zip(ks, pts):
        acc = he.point_add(acc, he.point_mul(k, p))
    enc = np.stack(
        [np.frombuffer(he.point_compress(p), np.uint8) for p in pts]
    ).astype(np.int32).T
    ok, P = pc.decompress(jnp.asarray(enc))
    assert bool(jnp.all(ok))
    scal = jnp.asarray(np.stack([bi.int_to_limbs_np(k, 20) for k in ks],
                                axis=-1))
    got = np.asarray(pc.compress(msm.msm(scal, P, 256)))[:, 0]
    assert got.astype(np.uint8).tobytes() == he.point_compress(acc)
