"""Handshake / NetworkProtocolVersion negotiation tests.

Reference: Node/NetworkProtocolVersion.hs + stdVersionDataNTN (Node.hs).
"""

import pytest

from ouroboros_consensus_tpu.miniprotocol import handshake
from ouroboros_consensus_tpu.miniprotocol.handshake import (
    HandshakeRefused,
    VersionData,
    negotiate,
)
from ouroboros_consensus_tpu.utils.sim import Channel, Sim

MAGIC = VersionData(network_magic=764824073)


def test_negotiate_highest_common():
    ours = {1: MAGIC, 2: MAGIC, 3: MAGIC}
    theirs = {1: MAGIC, 2: MAGIC}
    assert negotiate(ours, theirs) == (2, MAGIC)


def test_negotiate_refuses_disjoint_and_magic_mismatch():
    with pytest.raises(HandshakeRefused):
        negotiate({1: MAGIC}, {2: MAGIC})
    with pytest.raises(HandshakeRefused):
        negotiate({2: MAGIC}, {2: VersionData(network_magic=42)})


def test_handshake_tasks_agree():
    sim = Sim()
    req, rsp = Channel(delay=0.01), Channel(delay=0.01)
    c = sim.spawn(
        handshake.client(rsp, req, {1: MAGIC, 2: MAGIC}), "client"
    )
    s = sim.spawn(
        handshake.server(req, rsp, {2: MAGIC, 3: MAGIC}), "server"
    )
    sim.run(until=1.0)
    assert c.result == (2, MAGIC)
    assert s.result == (2, MAGIC)
    # the negotiated version gates the app bundle (NodeToNode.hs Apps)
    assert "txsubmission2" in handshake.NODE_TO_NODE_VERSIONS[2]
    assert "peersharing" not in handshake.NODE_TO_NODE_VERSIONS[2]


def test_handshake_refusal_propagates():
    from ouroboros_consensus_tpu.utils.sim import TaskFailed

    sim = Sim()
    req, rsp = Channel(), Channel()
    sim.spawn(handshake.client(rsp, req, {1: MAGIC}), "client")
    sim.spawn(handshake.server(req, rsp, {3: MAGIC}), "server")
    with pytest.raises(TaskFailed):
        sim.run(until=1.0)
