"""Handshake / NetworkProtocolVersion negotiation tests.

Reference: Node/NetworkProtocolVersion.hs + stdVersionDataNTN (Node.hs).
"""

import pytest

from ouroboros_consensus_tpu.miniprotocol import handshake
from ouroboros_consensus_tpu.miniprotocol.handshake import (
    HandshakeRefused,
    VersionData,
    negotiate,
)
from ouroboros_consensus_tpu.utils.sim import Channel, Sim

MAGIC = VersionData(network_magic=764824073)


def test_negotiate_highest_common():
    ours = {1: MAGIC, 2: MAGIC, 3: MAGIC}
    theirs = {1: MAGIC, 2: MAGIC}
    assert negotiate(ours, theirs) == (2, MAGIC)


def test_negotiate_refuses_disjoint_and_magic_mismatch():
    with pytest.raises(HandshakeRefused):
        negotiate({1: MAGIC}, {2: MAGIC})
    with pytest.raises(HandshakeRefused):
        negotiate({2: MAGIC}, {2: VersionData(network_magic=42)})


def test_handshake_tasks_agree():
    sim = Sim()
    req, rsp = Channel(delay=0.01), Channel(delay=0.01)
    c = sim.spawn(
        handshake.client(rsp, req, {1: MAGIC, 2: MAGIC}), "client"
    )
    s = sim.spawn(
        handshake.server(req, rsp, {2: MAGIC, 3: MAGIC}), "server"
    )
    sim.run(until=1.0)
    assert c.result == (2, MAGIC)
    assert s.result == (2, MAGIC)
    # the negotiated version gates the app bundle (NodeToNode.hs Apps)
    assert "txsubmission2" in handshake.NODE_TO_NODE_VERSIONS[2]
    assert "peersharing" not in handshake.NODE_TO_NODE_VERSIONS[2]


def test_handshake_refusal_propagates():
    from ouroboros_consensus_tpu.utils.sim import TaskFailed

    sim = Sim()
    req, rsp = Channel(), Channel()
    sim.spawn(handshake.client(rsp, req, {1: MAGIC}), "client")
    sim.spawn(handshake.server(req, rsp, {3: MAGIC}), "server")
    with pytest.raises(TaskFailed):
        sim.run(until=1.0)


def test_version_gated_app_bundle(tmp_path):
    """NodeToNode.hs:434-466: the negotiated version decides the app
    set — v1 peers run chainsync+blockfetch only; v3 peers add
    txsubmission2, keepalive and peersharing. The sync itself works
    through the bundle."""
    import tests.test_pipelining as tp
    from ouroboros_consensus_tpu.node.apps import connect_peers
    from ouroboros_consensus_tpu.utils.sim import Sim

    server = tp._mk_node(tmp_path, "server")
    client = tp._mk_node(tmp_path, "client")
    for b in tp._forge_chain(5):
        server.chain_db.add_block(b)

    sim = Sim()
    server.chain_db.runtime = sim
    client.chain_db.runtime = sim
    v1 = {1: MAGIC}
    v_all = {1: MAGIC, 2: MAGIC, 3: MAGIC}
    apps = connect_peers(sim, server, client, v_all, v1)
    assert apps.version == 1
    assert apps.protocols() == {"chainsync", "blockfetch"}
    sim.run(until=30.0)
    assert client.chain_db.tip_point().hash_ == server.chain_db.tip_point().hash_

    apps3 = connect_peers(Sim(), server, client, v_all, v_all)
    assert apps3.version == 3
    assert apps3.protocols() == {
        "chainsync", "blockfetch", "txsubmission", "keepalive", "peersharing"
    }


def test_node_to_client_bundle(tmp_path):
    """Network/NodeToClient.hs: v1 lacks LocalTxMonitor; v2 has it, and
    the negotiated version gates the query vocabulary end to end."""
    import tests.test_pipelining as tp
    from ouroboros_consensus_tpu.node.apps import node_to_client_apps
    from ouroboros_consensus_tpu.utils.sim import Recv, Send, Sim

    node = tp._mk_node(tmp_path, "n")
    apps1 = node_to_client_apps(node, 1)
    assert apps1.protocols() == {"localstatequery", "localtxsubmission"}
    apps2 = node_to_client_apps(node, 2)
    assert apps2.protocols() == {
        "localstatequery", "localtxsubmission", "localtxmonitor"
    }

    # a v1 session is refused the v2-gated query on the wire
    sim = Sim()
    for _o, name, gen in apps1.tasks:
        sim.spawn(gen, name)
    req, rsp = apps1.channels["localstatequery"]

    def client():
        yield Send(req, ("acquire", None))
        assert (yield Recv(rsp))[0] == "acquired"
        yield Send(req, ("query", "get_pool_distr", ()))
        r = yield Recv(rsp)
        assert r[0] == "failed" and "version 2" in r[1], r
        yield Send(req, ("query", "get_tip_slot", ()))
        r = yield Recv(rsp)
        assert r[0] == "result", r

    sim.spawn(client(), "client")
    sim.run(until=5.0)
