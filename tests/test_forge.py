"""Device-batched chain synthesis (PR 18, protocol/forge.py): the
forging differential plane.

The headline equation: the batched pipeline — windowed leader-election
sweeps + sequential assembly over just the elected slots — forges the
byte-identical chain the per-slot reference loop forges, for every
engine (loop / host / device), both proof formats, across epoch
boundaries, under empty elections, after a resume, and with chaos
detonating at the forge seams. Forged chains replay green through
validate_chain with zero gate declines, and the ForgeSpan plane counts
what happened."""

from __future__ import annotations

import os
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu import obs
from ouroboros_consensus_tpu.obs.warmup import WARMUP
from ouroboros_consensus_tpu.protocol import forge as forge_mod
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import chaos, fixtures
from ouroboros_consensus_tpu.testing.stubs import install_stub_forge
from ouroboros_consensus_tpu.tools import db_analyser as ana
from ouroboros_consensus_tpu.tools import db_synthesizer as synth


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    WARMUP.reset()
    obs.reset_for_tests()
    for var in ("OCT_CHAOS", "OCT_FORGE_DEVICE", "OCT_VRF_BATCH",
                "OCT_TRACE"):
        monkeypatch.delenv(var, raising=False)
    chaos.reset()
    synth._REPLAY_MEMO.clear()
    yield
    WARMUP.reset()
    obs.reset_for_tests()
    chaos.reset()
    synth._REPLAY_MEMO.clear()


def _params():
    # small epochs: a 150-slot run crosses two epoch boundaries, so the
    # window clamp at epoch edges (eta0 is epoch-constant) is exercised
    return praos.PraosParams(
        slots_per_kes_period=100,
        max_kes_evolutions=62,
        security_param=4,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=60,
        kes_depth=3,
    )


PARAMS = _params()
POOLS = [fixtures.make_pool(7, kes_depth=3),
         fixtures.make_pool(8, kes_depth=3)]
LVIEW = fixtures.make_ledger_view(POOLS)


def _forge(path, engine_env, limit, monkeypatch, *, pools=None,
           lview=None, txs_per_block=2):
    """Synthesize with the forging engine pinned by the env lever
    (None = unset -> the batched host default)."""
    if engine_env is None:
        monkeypatch.delenv("OCT_FORGE_DEVICE", raising=False)
    else:
        monkeypatch.setenv("OCT_FORGE_DEVICE", engine_env)
    return synth.synthesize(
        str(path), PARAMS, pools or POOLS, lview or LVIEW, limit,
        txs_per_block=txs_per_block, chunk_size=PARAMS.epoch_length,
    )


def _chain(db):
    imm = ana.open_immutable(str(db))
    return [(e.slot, e.block_no, e.hash_, raw)
            for e, raw in imm.stream_all()]


# ---------------------------------------------------------------------------
# the headline: pipeline == loop, byte for byte
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fmt", ["1", "0"], ids=["bc", "draft03"])
def test_host_pipeline_matches_loop_bytes(tmp_path, monkeypatch, fmt):
    """Batched host engine vs the per-slot reference loop: identical
    chain bytes, counters and final state across two epoch boundaries,
    in BOTH proof serializations."""
    monkeypatch.setenv("OCT_VRF_BATCH", fmt)
    r_loop = _forge(tmp_path / "loop", "0",
                    synth.ForgeLimit(slots=150), monkeypatch)
    r_host = _forge(tmp_path / "host", None,
                    synth.ForgeLimit(slots=150), monkeypatch)
    assert _chain(tmp_path / "loop") == _chain(tmp_path / "host")
    assert r_loop.n_blocks == r_host.n_blocks > 0
    assert r_loop.n_slots == r_host.n_slots == 150
    assert r_loop.final_state == r_host.final_state
    # the pipeline seals WALKED sidecars at forge time, same as the loop
    cols = [f for f in os.listdir(tmp_path / "host" / "immutable")
            if f.endswith(".cols")]
    assert cols


def test_blocks_limit_consumed_slots_match(tmp_path, monkeypatch):
    """The blocks limit trips mid-window: the pipeline must count only
    the slots up to and including the tripping block's — the loop's
    n_slots accounting, exactly."""
    r_loop = _forge(tmp_path / "loop", "0",
                    synth.ForgeLimit(blocks=23), monkeypatch)
    r_host = _forge(tmp_path / "host", None,
                    synth.ForgeLimit(blocks=23), monkeypatch)
    assert _chain(tmp_path / "loop") == _chain(tmp_path / "host")
    assert r_loop.n_blocks == r_host.n_blocks == 23
    assert r_loop.n_slots == r_host.n_slots
    assert r_loop.final_state == r_host.final_state


def test_epochs_limit_matches(tmp_path, monkeypatch):
    r_loop = _forge(tmp_path / "loop", "0",
                    synth.ForgeLimit(epochs=2), monkeypatch)
    r_host = _forge(tmp_path / "host", None,
                    synth.ForgeLimit(epochs=2), monkeypatch)
    assert _chain(tmp_path / "loop") == _chain(tmp_path / "host")
    assert r_loop.n_slots == r_host.n_slots == 2 * PARAMS.epoch_length
    assert r_loop.final_state == r_host.final_state


def test_empty_election_window(tmp_path, monkeypatch):
    """Zero-stake pools win nothing: both engines forge the same empty
    chain and still consume the whole slot budget."""
    dead = fixtures.make_ledger_view(POOLS, stakes=[Fraction(0)] * 2)
    r_loop = _forge(tmp_path / "loop", "0", synth.ForgeLimit(slots=80),
                    monkeypatch, lview=dead)
    r_host = _forge(tmp_path / "host", None, synth.ForgeLimit(slots=80),
                    monkeypatch, lview=dead)
    assert r_loop.n_blocks == r_host.n_blocks == 0
    assert r_loop.n_slots == r_host.n_slots == 80
    assert _chain(tmp_path / "loop") == _chain(tmp_path / "host") == []


def test_unknown_pool_treated_as_sigma_zero(tmp_path, monkeypatch):
    """A credential absent from the pool distribution never forges —
    the loop's `entry is None: continue` and the pipeline's sigma-0
    threshold rows are the same rule."""
    stranger = fixtures.make_pool(99, kes_depth=3)
    pools = POOLS + [stranger]
    # LVIEW only knows POOLS; `stranger` is the unknown credential
    r_loop = _forge(tmp_path / "loop", "0", synth.ForgeLimit(slots=100),
                    monkeypatch, pools=pools)
    r_host = _forge(tmp_path / "host", None, synth.ForgeLimit(slots=100),
                    monkeypatch, pools=pools)
    assert _chain(tmp_path / "loop") == _chain(tmp_path / "host")
    assert r_loop.final_state == r_host.final_state
    for _slot, _no, _hash, raw in _chain(tmp_path / "host"):
        assert stranger.vk_cold not in raw


# ---------------------------------------------------------------------------
# election engines as units
# ---------------------------------------------------------------------------


def test_elected_set_matches_reference_random_stakes():
    """Seeded random (irregular-denominator) stakes, 3 pools, 80 slots:
    the batched host election and the exact per-slot reference pick the
    same (slot, pool) set with the same VRF outputs."""
    import random

    rng = random.Random(42)
    pools = [fixtures.make_pool(20 + i, kes_depth=3) for i in range(3)]
    stakes = [Fraction(rng.randrange(1, 97), 291) for _ in range(3)]
    lview = fixtures.make_ledger_view(pools, stakes=stakes)
    import hashlib

    eta0 = hashlib.blake2b(b"forge-test-eta0", digest_size=32).digest()
    slots = range(0, 80)
    thr = forge_mod.pool_thresholds(PARAMS, lview, pools)
    host = forge_mod._elect_window_host(PARAMS, pools, thr, slots, eta0)
    ref = forge_mod._elect_window_reference(PARAMS, pools, lview, slots,
                                            eta0)
    assert [(e.slot, e.pool) for e in host] == [
        (e.slot, e.pool) for e in ref
    ]
    assert [e.is_leader for e in host] == [e.is_leader for e in ref]
    assert host  # seeded so the window is not vacuously empty


def test_engine_from_env(monkeypatch):
    monkeypatch.delenv("OCT_FORGE_DEVICE", raising=False)
    assert forge_mod.engine_from_env() == "host"
    assert forge_mod.engine_from_env("device") == "device"
    monkeypatch.setenv("OCT_FORGE_DEVICE", "0")
    assert forge_mod.engine_from_env("device") == "loop"
    monkeypatch.setenv("OCT_FORGE_DEVICE", "1")
    assert forge_mod.engine_from_env("host") == "device"


def test_kill_switch_restores_loop(tmp_path, monkeypatch):
    """OCT_FORGE_DEVICE=0 is the round-18 kill switch: the pipeline is
    never entered (an election dispatch would raise here), and the
    legacy loop forges the reference chain."""
    ref = _forge(tmp_path / "ref", "0", synth.ForgeLimit(blocks=20),
                 monkeypatch)

    def boom(*a, **kw):
        raise AssertionError("pipeline engaged under the kill switch")

    monkeypatch.setattr(forge_mod, "elect_window", boom)
    monkeypatch.setattr(forge_mod, "_elect_window_host", boom)
    r = _forge(tmp_path / "killed", "0", synth.ForgeLimit(blocks=20),
               monkeypatch)
    assert _chain(tmp_path / "killed") == _chain(tmp_path / "ref")
    assert r.final_state == ref.final_state


# ---------------------------------------------------------------------------
# the device engine under the stub family (tier-1) and for real (slow)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fmt", ["1", "0"], ids=["bc", "draft03"])
def test_device_stub_engine_byte_identical(tmp_path, monkeypatch, fmt):
    """Device sweep (stub hash-twin kernels — the real-crypto twin is
    the slow-tier test below) vs the reference loop under the SAME
    stubbed host crypto: byte-identical chains, ForgeSpan counters
    consistent, and the forge stages visible in the warmup forensics
    (the Perfetto warmup track's source)."""
    from ouroboros_consensus_tpu.protocol import batch as pbatch

    monkeypatch.setenv("OCT_VRF_BATCH", fmt)
    install_stub_forge(monkeypatch, bucket=256)
    # fresh first-execute ledger: the other format's cell already noted
    # forge_sweep's label, and _warm_timed only notes a stage once per
    # process — the warmup-stage assertion below needs its own note
    monkeypatch.setattr(pbatch, "_WARM_SEEN", set())
    r_loop = _forge(tmp_path / "loop", "0",
                    synth.ForgeLimit(slots=150), monkeypatch)
    rec = obs.install()
    try:
        r_dev = _forge(tmp_path / "dev", "1",
                       synth.ForgeLimit(slots=150), monkeypatch)
    finally:
        obs.uninstall()
    assert _chain(tmp_path / "loop") == _chain(tmp_path / "dev")
    assert r_loop.n_blocks == r_dev.n_blocks > 0
    assert r_loop.final_state == r_dev.final_state
    snap = rec.registry.snapshot()
    by_engine = {s["labels"]["engine"]: s["value"]
                 for s in snap["oct_forge_windows_total"]["samples"]}
    assert by_engine.get("device", 0) >= 1
    (elected,) = snap["oct_forge_elected_total"]["samples"]
    (signed,) = snap["oct_forge_signed_total"]["samples"]
    assert signed["value"] == r_dev.n_blocks
    assert elected["value"] >= signed["value"]
    # the sweep's first execute is a warmup stage note (lane-qualified);
    # a 150-slot run spans the neutral-nonce epoch-0 window AND real-
    # nonce windows, so BOTH sweep variants must have dispatched — and
    # neither through the recovery ladder
    stages = WARMUP.report()["stages"]
    assert any(k.startswith("forge_sweep:") for k in stages)
    assert any(k.startswith("forge_sweep-neutral:") for k in stages)
    assert not snap.get("oct_recovery_total", {}).get("samples", [])


def test_device_sweep_dispatches_under_neutral_nonce(tmp_path, monkeypatch):
    """Epoch 0 of a fresh chain elects under the NEUTRAL epoch nonce
    (PraosState() starts at None — mk_input_vrf hashes slot bytes
    alone). The device engine must dispatch the statically nonce-free
    sweep variant for those windows, not ride the recovery ladder to
    the host loop: a fallback would be byte-identical and therefore
    invisible to every differential, which is exactly why this pins
    the dispatch itself."""
    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.utils.trace import RecoveryEvent

    install_stub_forge(monkeypatch, bucket=256)
    monkeypatch.setattr(pbatch, "_WARM_SEEN", set())
    events = []
    monkeypatch.setattr(pbatch, "BATCH_TRACER", events.append)
    # slots < epoch_length: the WHOLE run stays in epoch 0 (neutral)
    limit = synth.ForgeLimit(slots=50)
    r_loop = _forge(tmp_path / "loop", "0", limit, monkeypatch)
    r_dev = _forge(tmp_path / "dev", "1", limit, monkeypatch)
    assert _chain(tmp_path / "loop") == _chain(tmp_path / "dev")
    assert r_loop.n_blocks == r_dev.n_blocks > 0
    assert not [e for e in events if isinstance(e, RecoveryEvent)]
    stages = WARMUP.report()["stages"]
    assert any(k.startswith("forge_sweep-neutral:") for k in stages)
    assert not any(k.startswith("forge_sweep:") for k in stages)


@pytest.mark.slow
def test_device_engine_real_crypto_byte_identical(tmp_path, monkeypatch):
    """The real thing: the full ECVRF prove sweep on the device engine
    (one ~4 min XLA:CPU compile at bucket 64) forges the byte-identical
    chain — measured 52/52 blocks equal on seed 7/8."""
    monkeypatch.setattr(forge_mod, "FORGE_BUCKET", 64)
    monkeypatch.setattr(forge_mod, "_JITS", {})
    r_loop = _forge(tmp_path / "loop", "0",
                    synth.ForgeLimit(slots=100), monkeypatch)
    r_dev = _forge(tmp_path / "dev", "1",
                   synth.ForgeLimit(slots=100), monkeypatch)
    assert _chain(tmp_path / "loop") == _chain(tmp_path / "dev")
    assert r_loop.final_state == r_dev.final_state


# ---------------------------------------------------------------------------
# forged chains replay green
# ---------------------------------------------------------------------------


def test_forged_chain_replays_green_zero_gate_declines(tmp_path,
                                                       monkeypatch):
    """A pipeline-forged chain is a first-class citizen of the verify
    side: validate_chain replays it end to end with no error and ZERO
    qualification-gate declines."""
    _forge(tmp_path / "db", None, synth.ForgeLimit(blocks=40),
           monkeypatch, txs_per_block=0)
    rec = obs.install()
    try:
        r = ana.revalidate(str(tmp_path / "db"), PARAMS, LVIEW,
                           backend="host", validate_all="stream")
    finally:
        obs.uninstall()
    assert r.error is None and r.n_valid == 40
    declines = rec.registry.snapshot().get("oct_gate_declines_total")
    assert sum(s["value"] for s in declines["samples"]) == 0


# ---------------------------------------------------------------------------
# resume: the memoized trusted fold
# ---------------------------------------------------------------------------


def test_resume_memoizes_trusted_fold(tmp_path, monkeypatch):
    """Resuming a store THIS process forged skips the whole-chain
    reupdate replay (the memo hit); a cleared memo falls through to the
    replay; both converge on the one-shot chain byte for byte."""
    calls = []
    real = synth._replay_forged_state

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(synth, "_replay_forged_state", spy)

    one = tmp_path / "oneshot"
    _forge(one, None, synth.ForgeLimit(blocks=30), monkeypatch)

    hit = tmp_path / "hit"
    _forge(hit, None, synth.ForgeLimit(blocks=15), monkeypatch)
    calls.clear()
    synth.synthesize(str(hit), PARAMS, POOLS, LVIEW,
                     synth.ForgeLimit(blocks=30), txs_per_block=2,
                     chunk_size=PARAMS.epoch_length, resume=True)
    assert calls == []  # the memo served the fold
    assert _chain(hit) == _chain(one)

    miss = tmp_path / "miss"
    _forge(miss, None, synth.ForgeLimit(blocks=15), monkeypatch)
    synth._REPLAY_MEMO.clear()
    calls.clear()
    synth.synthesize(str(miss), PARAMS, POOLS, LVIEW,
                     synth.ForgeLimit(blocks=30), txs_per_block=2,
                     chunk_size=PARAMS.epoch_length, resume=True)
    assert len(calls) == 1  # no memo: the replay fold ran once
    assert _chain(miss) == _chain(one)


def test_resume_memo_stale_tip_falls_through(tmp_path, monkeypatch):
    """A memo whose (slot, hash) no longer matches the on-disk tip —
    another writer, an external truncation — must NOT be trusted."""
    db = tmp_path / "db"
    _forge(db, None, synth.ForgeLimit(blocks=15), monkeypatch)
    key = os.path.realpath(str(db))
    assert key in synth._REPLAY_MEMO
    stale = synth._REPLAY_MEMO[key]
    synth._REPLAY_MEMO[key] = (stale[0] + 7, b"\x00" * 32) + stale[2:]
    calls = []
    real = synth._replay_forged_state

    def spy(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(synth, "_replay_forged_state", spy)
    synth.synthesize(str(db), PARAMS, POOLS, LVIEW,
                     synth.ForgeLimit(blocks=30), txs_per_block=2,
                     chunk_size=PARAMS.epoch_length, resume=True)
    assert len(calls) == 1  # stale memo rejected, replay ran
    one = tmp_path / "oneshot"
    _forge(one, None, synth.ForgeLimit(blocks=30), monkeypatch)
    assert _chain(db) == _chain(one)


# ---------------------------------------------------------------------------
# chaos at the forge seams: the recovery ladder
# ---------------------------------------------------------------------------


def _armed(monkeypatch, spec):
    monkeypatch.setenv("OCT_CHAOS", spec)
    chaos.reset()


def _recovery_actions(rec):
    fam = rec.registry.snapshot().get("oct_recovery_total")
    if not fam:
        return {}
    return {s["labels"]["action"]: s["value"] for s in fam["samples"]}


def test_forge_dispatch_device_error_rides_retry(tmp_path, monkeypatch):
    """One injected dispatch fault is absorbed by the ladder's retry —
    the chain is byte-identical to the unfaulted run and the episode is
    countable."""
    ref = _forge(tmp_path / "ref", None, synth.ForgeLimit(blocks=20),
                 monkeypatch)
    _armed(monkeypatch, "device-error@forge-dispatch:0")
    rec = obs.install()
    try:
        r = _forge(tmp_path / "db", None, synth.ForgeLimit(blocks=20),
                   monkeypatch)
    finally:
        obs.uninstall()
        monkeypatch.delenv("OCT_CHAOS")
        chaos.reset()
    assert _chain(tmp_path / "db") == _chain(tmp_path / "ref")
    assert r.final_state == ref.final_state
    acts = _recovery_actions(rec)
    assert acts.get("retry", 0) >= 1
    assert acts.get("recovered", 0) >= 1
    assert "host-reference" not in acts


def test_forge_dispatch_ladder_exhausts_to_host_reference(
        tmp_path, monkeypatch):
    """TWO consecutive dispatch faults defeat the retry (each fire
    advances the seam's sequence, so `:0,:1` hits both attempts): the
    window drops to the exact host-reference election and the chain is
    STILL byte-identical."""
    ref = _forge(tmp_path / "ref", None, synth.ForgeLimit(blocks=20),
                 monkeypatch)
    _armed(monkeypatch,
           "device-error@forge-dispatch:0,device-error@forge-dispatch:1")
    rec = obs.install()
    try:
        r = _forge(tmp_path / "db", None, synth.ForgeLimit(blocks=20),
                   monkeypatch)
    finally:
        obs.uninstall()
        monkeypatch.delenv("OCT_CHAOS")
        chaos.reset()
    assert _chain(tmp_path / "db") == _chain(tmp_path / "ref")
    assert r.final_state == ref.final_state
    acts = _recovery_actions(rec)
    assert acts.get("host-reference", 0) >= 1
    assert acts.get("recovered", 0) >= 1


def test_forge_dispatch_fault_on_device_engine_stub(tmp_path,
                                                    monkeypatch):
    """The ladder on the DEVICE engine (stub kernels): exhaustion lands
    on the host-reference floor — a dispatch fault can never change
    chain bytes, only cost."""
    install_stub_forge(monkeypatch, bucket=256)
    ref = _forge(tmp_path / "ref", "1", synth.ForgeLimit(blocks=20),
                 monkeypatch)
    _armed(monkeypatch,
           "device-error@forge-dispatch:0,device-error@forge-dispatch:1")
    try:
        _forge(tmp_path / "db", "1", synth.ForgeLimit(blocks=20),
               monkeypatch)
    finally:
        monkeypatch.delenv("OCT_CHAOS")
        chaos.reset()
    assert _chain(tmp_path / "db") == _chain(tmp_path / "ref")
