"""octrange tier-1 gate: interval/overflow + secret-taint certification
(analysis/absint.py, analysis/domains.py).

Layers:
  1. domain units — interval arithmetic, the widening ladder (the
     B_MAX=9500 rung is load-bearing), per-row canonicalization, taint
     joins;
  2. interpreter units on purpose-built tiny graphs — affine-counter
     pinning, genuine-overflow detection, truncating converts, per-row
     precision, scan-fixpoint widening;
  3. the PR 3 regression — `sum_mod_l` proves clean at the 87k-lane
     3-term boundary / 40x8192 / epoch shapes, and a fixture with the
     carry-normalization REVERTED is flagged at the exact accumulator
     eqn;
  4. taint fixtures — a seeded secret branch / secret gather index is
     caught, a select over secrets is clean, the sign path pins exactly
     its known fixed-base-ladder gather, the MSM argsort steers on
     PUBLIC wire marks only;
  5. the registry sweep — every certifiable graph proves at its
     fast-tier lanes (production 8192 for the lane-sensitive graphs)
     and matches its analysis/certified.json pin;
  6. the soundness property — random concrete inputs drawn inside the
     declared specs stay inside every top-level inferred interval
     (hypothesis when available, seeded-random fallback);
  7. CLI exit codes and machine-stable JSON.
"""

import functools
import inspect
import json
import os

import numpy as np
import pytest

from ouroboros_consensus_tpu.analysis import absint
from ouroboros_consensus_tpu.analysis import domains as D
from ouroboros_consensus_tpu.analysis import graphs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trace(fn, *args):
    import jax

    return jax.make_jaxpr(fn)(*args)


def _i32(*shape):
    import jax
    from jax import numpy as jnp

    return jax.ShapeDtypeStruct(shape, jnp.int32)


def _range_findings(fn, args, bounds):
    interp = absint.IntervalInterp("t")
    interp.run_closed(_trace(fn, *args), bounds)
    return absint._dedup(interp.findings), interp


def _taint_findings(fn, args, taints):
    interp = absint.TaintInterp("t")
    outs = interp.run_closed(_trace(fn, *args), taints)
    return absint._dedup(interp.findings), outs


# ---------------------------------------------------------------------------
# 1 — domains
# ---------------------------------------------------------------------------


def test_interval_arith():
    assert D.iv_mul((-3, 2), (4, 5)) == (-15, 10)
    assert D.iv_sub((0, 1), (2, 3)) == (-3, -1)
    assert D.iv_rem((-7, 9), (8, 8)) == (-7, 7)
    assert D.iv_shr((-8, 8), (1, 1)) == (-4, 4)  # arithmetic, like XLA
    assert D.iv_and((0, 300), (0, 15), (-(2**31), 2**31 - 1)) == (0, 15)


def test_widen_ladder_has_the_bmax_rung():
    # a loop carry observed growing past 8192 must land ON 9500 (the
    # nearly-normalized limb bound): overshooting to 2^14 would make
    # the next mul bound 20 * (2^14)^2 > 2^31 and kill the fixpoint
    assert D.iv_widen((0, 8192), (0, 8500)) == (0, 9500)
    assert D.iv_widen((0, 9500), (0, 9500)) == (0, 9500)  # stable


def test_widening_terminates_at_top():
    iv = (0, 1)
    for _ in range(len(D._LADDER) + 2):
        iv = D.iv_widen(iv, (iv[0], iv[1] * 3 + 1))
    assert D.iv_is_top(iv)


def test_rows_canonicalize_and_join():
    assert D.rows([(0, 1), (0, 1)]) == (0, 1)  # all-equal -> uniform
    r = D.rows([(0, 1), (0, 5)])
    assert isinstance(r, D.Rows)
    assert D.collapse(r) == (0, 5)
    # a join whose rows stay distinct keeps the structure…
    assert D.iv_join_any(r, (2, 3)) == D.Rows(((0, 3), (0, 5)))
    # …and one whose rows become all-equal re-canonicalizes to uniform
    assert D.iv_join_any(r, (2, 7)) == (0, 7)
    assert D.rows([]) == (0, 0)  # zero-extent axis


def test_taint_levels():
    t = D.taint_join(D.taint("wire", "sig"), D.taint("secret", "a"))
    assert D.taint_secret(t) == {"secret:a"}
    assert D.taint_wire(t) == {"wire:sig"}


# ---------------------------------------------------------------------------
# 2 — interpreter units
# ---------------------------------------------------------------------------


def test_fori_counter_is_pinned_not_widened():
    from jax import lax

    def f(x):
        return lax.fori_loop(0, 1000, lambda i, v: v + i * 0, x)

    findings, interp = _range_findings(f, (_i32(4),), [(0, 10)])
    assert findings == []


def test_genuine_int32_overflow_is_flagged():
    def f(x):
        return x * x

    findings, _ = _range_findings(f, (_i32(4),), [(0, 1 << 16)])
    assert [f_.kind for f_ in findings] == ["overflow"]
    # and stays quiet when the operand is proven narrow enough
    clean, _ = _range_findings(f, (_i32(4),), [(0, 46340)])
    assert clean == []


def test_truncating_convert_is_flagged():
    from jax import numpy as jnp

    def f(x):
        return x.astype(jnp.int8)

    findings, _ = _range_findings(f, (_i32(4),), [(0, 300)])
    assert [f_.kind for f_ in findings] == ["truncate"]
    clean, _ = _range_findings(f, (_i32(4),), [(0, 100)])
    assert clean == []


def test_unsigned_wrap_is_not_a_finding():
    from jax import numpy as jnp

    def f(x):
        y = x.astype(jnp.uint32)
        return y * y  # wraps mod 2^32: defined XLA semantics

    findings, _ = _range_findings(f, (_i32(4),), [(0, 1 << 20)])
    assert findings == []


def test_per_row_precision_certifies_the_fold_idiom():
    """The limbs.mul safety story in miniature: a FOLD^2-weighted row
    whose operand row is small. The whole-tensor bound (9500 * FOLD^2 >
    2^31) cannot certify this; the per-row bound (1 * FOLD^2) can."""
    fold2 = 369664  # (19 * 2^5)^2
    col = np.concatenate(
        [np.full((19, 1), 9500, np.int32), np.ones((1, 1), np.int32)]
    )
    wts = np.concatenate(
        [np.ones((19, 1), np.int32), np.full((1, 1), fold2, np.int32)]
    )

    def f(x):
        return (x + col) * wts

    findings, _ = _range_findings(f, (_i32(20, 4),), [(0, 0)])
    assert findings == []
    # sanity: the whole-tensor product really is out of range
    assert 9500 * fold2 > 2**31 - 1


def test_scan_fixpoint_widens_to_a_stable_bound():
    from jax import lax
    from jax import numpy as jnp

    def f(x):
        def body(c, _):
            return jnp.minimum(c + 1, 9000), c

        c, ys = lax.scan(body, x, None, length=100000)
        return c

    findings, interp = _range_findings(f, (_i32(),), [(0, 1)])
    assert findings == []


def test_unknown_primitive_reports_not_crashes():
    import jax

    def f(x):
        return jax.nn.softmax(x.astype("float32"))

    findings, _ = _range_findings(f, (_i32(4),), [(0, 10)])
    # float ops have no transfers: reported as unknown-prim, never an
    # exception, and certification stays honest (graph not proven)
    assert all(f_.kind == "unknown-prim" for f_ in findings)


# ---------------------------------------------------------------------------
# 3 — the PR 3 sum_mod_l regression
# ---------------------------------------------------------------------------


def test_sum_mod_l_proofs_hold():
    """The shipped kernel (per-term carry normalization before the
    cross-term add) proves no-overflow at the 87k 3-term boundary, the
    40x8192 max-term shape and the 1M-headers-equivalent epoch shape."""
    for name in ("sum_mod_l_3t", "sum_mod_l_40t", "sum_mod_l_epoch"):
        for r in _certified(name):
            if r.domain == "range":
                assert r.ok, (name, [f.format() for f in r.findings])


def _reverted_sum_mod_l(terms):
    """The PR 3 bug, resurrected: lane sums accumulated WITHOUT the
    per-term carry normalization. 3 x 87381 max-limb terms push the
    accumulator rows past 2^31."""
    from jax import numpy as jnp

    from ouroboros_consensus_tpu.ops.pk import limbs as fe

    acc = None
    for t in terms:
        s = jnp.sum(t, axis=-1, keepdims=True)
        wide = jnp.concatenate(
            [s, jnp.zeros((40 - fe.NLIMBS, 1), jnp.int32)], axis=0
        )
        acc = wide if acc is None else acc + wide  # REVERT-MARK
    acc, _ = fe._seq_carry(acc)
    return fe.barrett_reduce40(acc)


def test_reverted_sum_mod_l_is_flagged_at_the_accumulator_eqn():
    def f(a, b, c):
        return _reverted_sum_mod_l([a, b, c])

    # 3 x 87400 = 262,200 lane-terms: just PAST the 2^31/8191 = 262,177
    # threshold (the shipped kernel's per-term normalization proves
    # clean at any lane count; the reverted accumulator overflows here —
    # and is still clean at the 3 x 87381 = 262,143 boundary shape, which
    # is why the certified sweep pins that shape as the showcase)
    s = _i32(20, 87400)
    findings, _ = _range_findings(f, (s, s, s), [(0, 8191)] * 3)
    overflows = [f_ for f_ in findings if f_.kind == "overflow"]
    assert overflows, findings
    # the specific eqn: the un-normalized cross-term add at REVERT-MARK
    src_lines, first = inspect.getsourcelines(_reverted_sum_mod_l)
    mark = first + next(
        i for i, ln in enumerate(src_lines) if "REVERT-MARK" in ln
    )
    assert any(
        f_.prim == "add" and f"tests/test_absint.py:{mark}" in f_.src
        for f_ in overflows
    ), [f_.format() for f_ in overflows]


# ---------------------------------------------------------------------------
# 4 — taint fixtures
# ---------------------------------------------------------------------------


def test_secret_branch_is_caught():
    from jax import lax

    def f(s):
        return lax.cond(s[0] > 0, lambda: 1, lambda: 0)

    findings, _ = _taint_findings(
        f, (_i32(4),), [D.taint("secret", "k")]
    )
    assert [f_.kind for f_ in findings] == ["taint-branch"]


def test_secret_index_is_caught():
    def f(tab, s):
        return tab[s[0]]

    findings, _ = _taint_findings(
        f, (_i32(16), _i32(4)),
        [D.NO_TAINT, D.taint("secret", "k")],
    )
    assert "taint-index" in {f_.kind for f_ in findings}


def test_select_over_secret_is_clean():
    from jax import numpy as jnp

    def f(s, a, b):
        return jnp.where(s > 0, a, b)

    findings, outs = _taint_findings(
        f, (_i32(4), _i32(4), _i32(4)),
        [D.taint("secret", "k"), D.NO_TAINT, D.NO_TAINT],
    )
    assert findings == []  # select_n is constant-time: not a branch
    assert outs[0] == {"secret:k"}  # but the output stays tainted


def test_wire_steering_is_recorded_not_flagged():
    def f(tab, w):
        return tab[w[0]]

    findings, _ = _taint_findings(
        f, (_i32(16), _i32(4)), [D.NO_TAINT, D.taint("wire", "hdr")]
    )
    assert findings == []  # wire data is public: access is allowed


def test_sign_path_pins_exactly_the_ladder_gather():
    """The ed25519 sign path carries REAL secrets; its one known
    secret-indexed access (the XLA-twin fixed-base ladder's window
    table gather in ops/curve.py) is pinned in certified.json — any
    second access is a ratchet violation."""
    r = absint.certify_taint("ed25519_sign")
    kinds = {f.key() for f in r.findings}
    pinned = set(
        absint.load_certified()["graphs"]["ed25519_sign"]["taint_findings"]
    )
    assert kinds == pinned
    assert all("ops/curve.py" in f.src for f in r.findings)
    assert absint.check_certified([r]) == []


# ---------------------------------------------------------------------------
# 5 — the registry sweep (the acceptance gate)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _certified(name):
    return tuple(absint.certify_graph(name, "fast"))


def test_certified_json_covers_every_certifiable_graph():
    pins = absint.load_certified()["graphs"]
    assert sorted(pins) == absint.certifiable_graphs()


# cheap graphs certify inline in tier-1 — including msm at the
# production 8192-lane window, the per-lane crypto cores and every
# sum_mod_l production shape. The three big production-shape certs
# (composed BC core, aggregate, sharded spmd: ~145 s of trace +
# interpret on this box) ride the SLOW tier since round 8, as do the
# fully-interior graphs (vrf_core/vrf_bc_core trace inside the composed
# cores; draft-03 verify_praos_core shares every kernel with the bc
# twin). Their certificates stay enforced every run by the ratchet:
# scripts/lint.py's full sweep exits 4 on any lost proof, and
# test_certified_json_covers_every_certifiable_graph pins the
# certified.json surface inline.
_FAST_GRAPHS = [
    "ed_core", "kes_core", "finish_core", "msm", "packed_unpack",
    "verdict_reduce", "mul_mod_l", "sum_mod_l_3t", "sum_mod_l_40t",
    "sum_mod_l_epoch", "ed25519_sign", "forge_sign",
]
_HEAVY_GRAPHS = [
    "verify_praos_core_bc", "aggregate_core", "spmd_sharded_verify",
    "forge_sweep",
]
_INTERIOR_GRAPHS = ["vrf_core", "vrf_bc_core", "verify_praos_core"]


def _assert_certified(name):
    reports = list(_certified(name))
    for r in reports:
        if r.domain == "range":
            assert r.ok, (
                f"{name}@{r.lanes} [range]: "
                + "; ".join(f.format() for f in r.findings)
            )
    # taint reports may carry PINNED findings (the sign path's ladder
    # gather); the ratchet — not bare ok — is the acceptance condition
    assert absint.check_certified(reports) == []


@pytest.mark.parametrize("name", _FAST_GRAPHS)
def test_certified_fast(name):
    _assert_certified(name)


@pytest.mark.slow
@pytest.mark.parametrize("name", _HEAVY_GRAPHS)
def test_certified_heavy(name):
    _assert_certified(name)


@pytest.mark.slow
@pytest.mark.parametrize("name", _INTERIOR_GRAPHS)
def test_certified_interior(name):
    _assert_certified(name)


def test_msm_argsort_steers_on_public_wire_only():
    """The documented argument for the MSM's per-window argsort: its
    keys are Fiat–Shamir coefficients — deterministic functions of
    PUBLIC wire bytes — so the data-dependent permutation cannot leak a
    secret. The certificate records the steering sites; none may carry
    a secret mark."""
    taint = [r for r in _certified("msm") if r.domain == "taint"][0]
    assert taint.ok
    sort_sites = [w for w in taint.wire_steered if "sort@" in w]
    assert sort_sites and all("ops/pk/msm.py" in w for w in sort_sites)
    assert all("secret:" not in w for w in taint.wire_steered)


def test_check_certified_ratchet_semantics():
    mk = lambda **kw: absint.Report(  # noqa: E731
        graph="g", domain="range", lanes=None, ok=True, findings=[], **kw
    )
    f = absint.Finding("overflow", "g", "add", "x.py:1", "boom")
    pins = {"graphs": {"g": {"range": "proven", "taint": "clean",
                             "taint_findings": []}}}
    assert absint.check_certified([mk()], pins) == []
    # lost proof
    lost = absint.Report("g", "range", None, False, [f])
    assert any("LOST" in v for v in absint.check_certified([lost], pins))
    # new taint finding on a clean pin
    t = absint.Report("g", "taint", None, False, [f])
    assert any("pinned clean" in v
               for v in absint.check_certified([t], pins))
    # stale pin (finding no longer fires)
    pins2 = {"graphs": {"g": {"range": "proven", "taint": "pinned",
                              "taint_findings": [f.key()]}}}
    t2 = absint.Report("g", "taint", None, True, [])
    assert any("stale" in v for v in absint.check_certified([t2], pins2))
    # unpinned graph
    assert any("no certified.json entry" in v
               for v in absint.check_certified(
                   [absint.Report("h", "range", None, True, [])], pins))


def test_graph_sources_exist():
    """--changed selection can only work if the source maps stay
    truthful: every listed module must exist, every graph must be
    listed."""
    srcs = dict(graphs.GRAPH_SOURCES)
    srcs.update(absint.AUX_SOURCES)
    assert set(srcs) == set(absint.certifiable_graphs())
    for name, files in srcs.items():
        for f in files:
            assert os.path.exists(os.path.join(REPO, f)), (name, f)


def test_changed_selection():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_gate", os.path.join(REPO, "scripts", "lint.py")
    )
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    sel = lint._select_graphs({"ouroboros_consensus_tpu/ops/pk/msm.py"})
    assert sel == ["aggregate_core", "aggregate_vrf_core", "msm"]
    assert lint._select_graphs(set()) == []
    # machinery edits invalidate everything -> full sweep
    assert lint._select_graphs(
        {"ouroboros_consensus_tpu/analysis/domains.py"}
    ) is None
    # an unrelated file selects nothing
    assert lint._select_graphs({"README.md"}) == []


# ---------------------------------------------------------------------------
# 6 — soundness property
# ---------------------------------------------------------------------------

# (name, lanes): traced SMALL — concrete eqn-by-eqn execution under
# disable_jit pays an eager XLA compile per unique (prim, shape), so
# production tiles would burn minutes on op compiles alone. The
# abstract semantics being checked are shape-generic; small lanes lose
# no property coverage.
_SOUND_GRAPHS = [("mul_mod_l", 48), ("sum_mod_l_3t", 48),
                 ("verdict_reduce", None)]


def _draw_inputs(closed, bounds, rng):
    arrays = []
    for v, (lo, hi) in zip(closed.jaxpr.invars, bounds):
        aval = v.aval
        a = rng.integers(lo, hi, size=aval.shape, endpoint=True)
        arrays.append(np.asarray(a).astype(aval.dtype))
    return arrays


def _check_soundness(seed):
    """Concrete execution, eqn by eqn, of each sample graph: every
    TOP-LEVEL intermediate must lie inside the interpreter's inferred
    interval for that eqn (nested computations are covered through
    their call-eqn outputs)."""
    import jax

    rng = np.random.default_rng(seed)
    shapes = absint.load_shapes()
    for name, lanes in _SOUND_GRAPHS:
        closed = absint._trace_any(name, lanes)
        bounds = absint.input_intervals(name, closed, shapes)
        interp = absint.IntervalInterp(name)
        interp.eqn_log = []
        interp.run_closed(closed, bounds)
        assert not [f for f in interp.findings], name

        env = {}
        for v, c in zip(closed.jaxpr.constvars, closed.consts):
            env[v] = c
        for v, a in zip(
            closed.jaxpr.invars, _draw_inputs(closed, bounds, rng)
        ):
            env[v] = a

        def read(atom):
            return atom.val if hasattr(atom, "val") else env[atom]

        log = iter(interp.eqn_log)
        with jax.disable_jit():
            for eqn in closed.jaxpr.eqns:
                subfuns, bind_params = eqn.primitive.get_bind_params(
                    eqn.params
                )
                outs = eqn.primitive.bind(
                    *subfuns, *[read(a) for a in eqn.invars],
                    **bind_params,
                )
                if not eqn.primitive.multiple_results:
                    outs = [outs]
                logged_eqn, abs_outs = next(log)
                assert logged_eqn is eqn
                for v, o, a in zip(eqn.outvars, outs, abs_outs):
                    env[v] = o
                    arr = np.asarray(o)
                    if arr.size == 0 or not (
                        np.issubdtype(arr.dtype, np.integer)
                        or arr.dtype == np.bool_
                    ):
                        continue
                    for i, (lo, hi) in enumerate(
                        D.rows_of(a, arr.shape[0])
                        if isinstance(a, D.Rows) else [D.collapse(a)]
                    ):
                        sl = arr[i] if isinstance(a, D.Rows) else arr
                        assert lo <= int(sl.min()) and int(sl.max()) <= hi, (
                            name, eqn.primitive.name, i, (lo, hi),
                            (int(sl.min()), int(sl.max())),
                        )


@pytest.mark.slow
def test_soundness_property_tier1():
    """One seeded draw (pays the eager-op compile cache warmup once);
    slow tier since round 8 together with the multi-seed sweep — the
    soundness HARNESS itself stays covered inline by the domain/interp
    unit tests and the seeded-revert fixture."""
    _check_soundness(0xA5)


try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    @pytest.mark.slow
    @given(seed=st.integers(0, 2**32 - 1))
    @settings(max_examples=5, deadline=None)
    def test_soundness_property(seed):
        _check_soundness(seed)
except ImportError:  # seeded-random fallback, same property
    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [0x5A17, 0xC0FFEE, 0xD15EA5E])
    def test_soundness_property(seed):
        _check_soundness(seed)


# ---------------------------------------------------------------------------
# 7 — CLI
# ---------------------------------------------------------------------------


def test_cli_range_json_is_machine_stable(capsys):
    from ouroboros_consensus_tpu.analysis.__main__ import main

    rc = main(["range", "--graphs", "mul_mod_l", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    parsed = json.loads(out)
    assert parsed["ok"] is True
    # sorted keys end to end: re-serialization is byte-identical
    assert out.strip() == json.dumps(parsed, indent=2, sort_keys=True)


def test_cli_certification_failure_exits_4():
    from ouroboros_consensus_tpu.analysis.__main__ import main

    # 300000 lanes is past the kernel's own t <= 2^17 shape guard: the
    # graph cannot even trace at that sweep, so certification fails
    # (trace-error finding) and the exit code must be the distinct
    # certification value — not a crash, not the usage code
    rc = main(["range", "--graphs", "sum_mod_l_3t",
               "--lanes", "300000", "--no-ratchet", "--json"])
    assert rc == 4


@pytest.mark.slow  # ~8 s of graph re-trace; exit codes 2/4 stay inline
def test_cli_budget_violation_exits_3(tmp_path, capsys):
    from ouroboros_consensus_tpu.analysis.__main__ import main

    tight = {
        "graphs": {},
        "point_ops": {
            "mul_mod_l_like": {"at_lanes": 1, "lane_ops_per_lane": 0},
        },
    }
    # an impossible point-op ceiling on a real graph
    budgets = json.loads(json.dumps(tight))
    budgets["point_ops"] = {
        "msm": {"at_lanes": 4, "lane_ops_per_lane": 0.0},
    }
    p = tmp_path / "budgets.json"
    p.write_text(json.dumps(budgets))
    rc = main(["pointops", "--budgets", str(p), "--json"])
    assert rc == 3


def test_cli_usage_error_exits_2():
    from ouroboros_consensus_tpu.analysis.__main__ import main

    with pytest.raises(SystemExit) as e:
        main(["range", "--tier", "bogus"])
    assert e.value.code == 2
