"""Deterministic sim runtime: ordering, channels with delay, events,
reproducibility (the io-sim analog's core guarantees)."""

import pytest

from ouroboros_consensus_tpu.utils.sim import (
    Channel,
    Event,
    Fire,
    Recv,
    Send,
    Sim,
    Sleep,
    Spawn,
    Stop,
    TaskFailed,
    run_sim,
)


def test_sleep_ordering():
    log = []

    def t(name, dt):
        yield Sleep(dt)
        log.append((name, dt))

    run_sim([("a", t("a", 3)), ("b", t("b", 1)), ("c", t("c", 2))])
    assert log == [("b", 1), ("c", 2), ("a", 3)]


def test_same_time_fifo():
    log = []

    def t(name):
        yield Sleep(5)
        log.append(name)

    run_sim([("a", t("a")), ("b", t("b")), ("c", t("c"))])
    assert log == ["a", "b", "c"]  # spawn order preserved at equal times


def test_channel_delay():
    chan = Channel(delay=2.5)
    got = []

    def sender():
        yield Send(chan, "hello")

    def receiver(sim):
        msg = yield Recv(chan)
        got.append((sim.now, msg))

    sim = Sim()
    sim.spawn(receiver(sim), "rx")
    sim.spawn(sender(), "tx")
    sim.run()
    assert got == [(2.5, "hello")]


def test_channel_fifo_two_messages():
    chan = Channel(delay=1.0)
    got = []

    def sender():
        yield Send(chan, 1)
        yield Send(chan, 2)

    def receiver():
        a = yield Recv(chan)
        b = yield Recv(chan)
        got.extend([a, b])

    run_sim([("rx", receiver()), ("tx", sender())])
    assert got == [1, 2]


def test_event_broadcast():
    ev = Event()
    woken = []

    def waiter(name):
        yield Wait(ev)
        woken.append(name)

    from ouroboros_consensus_tpu.utils.sim import Wait

    def firer():
        yield Sleep(1)
        yield Fire(ev)

    run_sim([("w1", waiter("w1")), ("w2", waiter("w2")), ("f", firer())])
    assert woken == ["w1", "w2"]


def test_spawn_and_stop():
    log = []

    def child():
        yield Sleep(1)
        log.append("child")

    def parent():
        yield Spawn(child(), "child")
        log.append("parent")
        yield Stop()
        log.append("unreachable")

    run_sim([("p", parent())])
    assert log == ["parent", "child"]


def test_task_failure_propagates():
    def bad():
        yield Sleep(1)
        raise ValueError("boom")

    with pytest.raises(TaskFailed) as ei:
        run_sim([("bad", bad())])
    assert isinstance(ei.value.exc, ValueError)


def test_determinism_replay():
    """Two identical runs produce identical event logs."""

    def program(log):
        chan = Channel(delay=0.5)

        def ping():
            for i in range(3):
                yield Send(chan, i)
                yield Sleep(1)

        def pong(sim):
            for _ in range(3):
                m = yield Recv(chan)
                log.append((sim.now, m))

        sim = Sim()
        sim.spawn(pong(sim), "pong")
        sim.spawn(ping(), "ping")
        sim.run()
        return log

    assert program([]) == program([])


def test_seeded_schedule_exploration():
    """SURVEY §5.2: a Sim seed permutes same-time wakeups — different
    seeds exercise different interleavings, every seed is replayable."""
    from ouroboros_consensus_tpu.utils.sim import Sim, Sleep

    def run(seed):
        sim = Sim(seed=seed)
        order = []

        def worker(i):
            for _ in range(3):
                order.append(i)
                yield Sleep(1.0)  # all workers wake at the same instants

        for i in range(4):
            sim.spawn(worker(i), f"w{i}")
        sim.run()
        return order

    baseline = run(None)
    assert baseline == [0, 1, 2, 3] * 3  # FIFO without a seed
    seeds = {s: run(s) for s in (1, 2, 3, 4, 5)}
    # replayable: same seed, same schedule
    for s, o in seeds.items():
        assert run(s) == o
    # explores: some seed deviates from FIFO
    assert any(o != baseline for o in seeds.values())
    # every interleaving is fair: each worker still ran 3 times
    for o in seeds.values():
        assert sorted(o) == sorted(baseline)


def test_recv_timeout_effect():
    """RecvTimeout: resumes with TIMEOUT when nothing arrives; delivers
    the message when it does; a stale timer never fires into a LATER
    park on the same channel."""
    from ouroboros_consensus_tpu.utils.sim import (
        TIMEOUT, Channel, Recv, RecvTimeout, Send, Sim, Sleep,
    )

    log = []

    def consumer(ch):
        got = yield RecvTimeout(ch, 1.0)
        log.append(("first", got is TIMEOUT))
        # second park on the SAME channel: the first timer (still in the
        # run queue if it lost the race) must not fire into this one
        got = yield RecvTimeout(ch, 5.0)
        log.append(("second", got))

    def producer(ch):
        yield Sleep(2.0)
        yield Send(ch, "late")

    sim = Sim()
    ch = Channel()
    sim.spawn(consumer(ch), "c")
    sim.spawn(producer(ch), "p")
    sim.run()
    assert log == [("first", True), ("second", "late")]

    # timely delivery: no timeout
    log2 = []

    def consumer2(ch):
        got = yield RecvTimeout(ch, 5.0)
        log2.append(got)

    def producer2(ch):
        yield Sleep(0.5)
        yield Send(ch, "ontime")

    sim = Sim()
    ch = Channel()
    sim.spawn(consumer2(ch), "c")
    sim.spawn(producer2(ch), "p")
    sim.run()
    assert log2 == ["ontime"]


def test_keepalive_timeout_disconnects_peer():
    """A silent keepalive server trips KeepAliveTimeout, classified as
    a PEER disconnect by peer_guard (RethrowPolicy parity)."""
    from ouroboros_consensus_tpu.miniprotocol import txsubmission
    from ouroboros_consensus_tpu.miniprotocol.rethrow import peer_guard
    from ouroboros_consensus_tpu.utils.sim import Channel, Recv, Sim

    sim = Sim()
    rx, tx = Channel(), Channel()
    disconnected = []

    def dead_server():
        yield Recv(tx)  # swallow the cookie, never answer

    sim.spawn(dead_server(), "dead")
    sim.spawn(
        peer_guard(
            txsubmission.keepalive_client(rx, tx, timeout=3.0),
            "ka", lambda s: None, lambda: disconnected.append(True),
        ),
        "ka",
    )
    sim.run(until=20)
    assert disconnected == [True]
