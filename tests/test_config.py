"""TopLevelConfig bundle + hard-fork-aware slot clock.

Reference: Config.hs:38 (TopLevelConfig, configSecurityParam) and
BlockchainTime/WallClock/HardFork.hs:9 (hardForkBlockchainTime — the
clock re-queries the HFC summary so era slot-length changes take
effect at the era boundary).
"""

from fractions import Fraction

from ouroboros_consensus_tpu.config import (
    BlockConfig,
    HardForkSlotClock,
    StorageConfig,
    TopLevelConfig,
)
from ouroboros_consensus_tpu.hardfork.history import EraParams, summarize
from ouroboros_consensus_tpu.ledger import mock as mock_ledger
from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.testing import fixtures


def test_top_level_config_bundle():
    params = praos.PraosParams(
        slots_per_kes_period=100, max_kes_evolutions=62, security_param=7,
        active_slot_coeff=Fraction(1, 2), epoch_length=100, kes_depth=3,
    )
    pool = fixtures.make_pool(0, kes_depth=3)
    lview = fixtures.make_ledger_view([pool])
    cfg = TopLevelConfig(
        protocol=params,
        ledger=mock_ledger.MockConfig(lview, params.stability_window),
        block=BlockConfig(protocol_version=(10, 0)),
        storage=StorageConfig(chunk_size=50),
    )
    assert cfg.security_param == 7  # configSecurityParam projection
    assert cfg.storage.chunk_size == 50
    assert cfg.block.protocol_version == (10, 0)


def test_hardfork_slot_clock_era_lengths():
    """Era A: 2-second slots for 1 epoch (10 slots); era B: 1-second
    slots. The clock must place wallclock times correctly across the
    boundary — a fixed-length clock would be wrong in era B."""
    summary = summarize(
        Fraction(0),
        [
            EraParams(epoch_size=10, slot_length=Fraction(2), safe_zone=2),
            EraParams(epoch_size=10, slot_length=Fraction(1), safe_zone=2),
        ],
        [1, None],  # era A ends at epoch 1
    )
    clock = HardForkSlotClock(summary)
    # era A: slot s starts at 2s
    assert clock.start_of(3) == 6.0
    assert clock.slot_of(7.9) == 3
    # boundary: slot 10 starts at 20.0; era B slots are 1s
    assert clock.start_of(10) == 20.0
    assert clock.start_of(15) == 25.0
    assert clock.slot_of(25.5) == 15
