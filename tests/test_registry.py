"""ResourceRegistry + RAWLock + chain-sel combinators.

Reference: Util/ResourceRegistry.hs (release order, linked tasks),
Util/MonadSTM/RAWLock.hs (reference tests run schedules under io-sim:
Test/Consensus/Util/MonadSTM/RAWLock.hs), Protocol/ModChainSel.hs.
"""

import pytest

from ouroboros_consensus_tpu.utils.registry import (
    RAWLock,
    RegistryClosed,
    ResourceRegistry,
)
from ouroboros_consensus_tpu.utils.sim import Sim, Sleep


def test_registry_releases_lifo():
    order = []
    with ResourceRegistry() as reg:
        reg.allocate(lambda: "a", lambda r: order.append(r))
        reg.allocate(lambda: "b", lambda r: order.append(r))
        reg.allocate(lambda: "c", lambda r: order.append(r))
    assert order == ["c", "b", "a"]
    with pytest.raises(RegistryClosed):
        reg.allocate(lambda: "d", lambda r: None)


def test_registry_kills_linked_tasks():
    sim = Sim()
    reg = ResourceRegistry(sim)

    ticks = []

    def ticker():
        while True:
            ticks.append(sim.now)
            yield Sleep(1.0)

    def closer():
        yield Sleep(3.5)
        reg.close()

    reg.fork_linked(ticker(), "ticker")
    sim.spawn(closer(), "closer")
    sim.run(until=10.0)
    # ticker ran at 0,1,2,3 then died with the registry
    assert ticks == [0.0, 1.0, 2.0, 3.0]


def test_rawlock_invariants():
    """Readers may overlap each other and ONE appender; writers are
    exclusive and not starved by a steady reader stream."""
    sim = Sim()
    lock = RAWLock(sim)
    trace = []

    def invariant():
        assert lock._readers >= 0
        if lock._writer:
            assert lock._readers == 0 and not lock._appender

    def reader(i):
        for _ in range(3):
            yield from lock.acquire_read()
            invariant()
            trace.append(("r", i, sim.now))
            yield Sleep(0.3)
            lock.release_read()
            yield Sleep(0.1)

    def appender():
        for _ in range(2):
            yield from lock.acquire_append()
            invariant()
            trace.append(("a", sim.now))
            yield Sleep(0.4)
            lock.release_append()
            yield Sleep(0.1)

    def writer():
        yield Sleep(0.05)  # arrive while readers hold the lock
        yield from lock.acquire_write()
        invariant()
        trace.append(("w", sim.now))
        yield Sleep(0.2)
        lock.release_write()

    for i in range(3):
        sim.spawn(reader(i), f"reader{i}")
    sim.spawn(appender(), "appender")
    sim.spawn(writer(), "writer")
    sim.run(until=30.0)

    # the writer got in (no starvation) and everyone finished their work
    assert any(op[0] == "w" for op in trace)
    assert sum(1 for op in trace if op[0] == "r") == 9
    assert sum(1 for op in trace if op[0] == "a") == 2


def test_mod_chain_sel_overrides_order(tmp_path):
    """ModChainSel: LOWEST slot tip preferred — chain selection follows
    the substituted order while validation stays Praos."""
    from dataclasses import replace
    from fractions import Fraction

    from ouroboros_consensus_tpu.block import forge_block
    from ouroboros_consensus_tpu.ledger import ExtLedger
    from ouroboros_consensus_tpu.ledger import mock as mock_ledger
    from ouroboros_consensus_tpu.protocol import praos
    from ouroboros_consensus_tpu.protocol.instances import (
        ModChainSel,
        PraosProtocol,
    )
    from ouroboros_consensus_tpu.storage.open import open_chaindb
    from ouroboros_consensus_tpu.testing import fixtures

    params = praos.PraosParams(
        slots_per_kes_period=100, max_kes_evolutions=62, security_param=5,
        active_slot_coeff=Fraction(1), epoch_length=10_000, kes_depth=2,
    )
    pools = [fixtures.make_pool(i, kes_depth=2) for i in range(2)]
    lview = fixtures.make_ledger_view(pools)
    eta = b"\x22" * 32
    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(lview, params.stability_window)
    )
    inner = PraosProtocol(params, use_device_batch=False)
    proto = ModChainSel(
        inner,
        select_view_fn=lambda h: (h.block_no, -h.slot),
        compare_fn=lambda o, t: (
            ((t > o) - (t < o))
            if None not in (o, t)
            else (0 if o == t else (1 if o is None else -1))
        ),
    )
    ext = ExtLedger(ledger, proto)
    st = ext.genesis(ledger.genesis_state([]))
    st = replace(
        st,
        header_state=replace(
            st.header_state,
            chain_dep_state=replace(
                st.header_state.chain_dep_state, epoch_nonce=eta
            ),
        ),
    )
    db = open_chaindb(str(tmp_path / "db"), ext, st, params.security_param)
    late = forge_block(params, pools[0], slot=10, block_no=0,
                       prev_hash=None, epoch_nonce=eta)
    early = forge_block(params, pools[1], slot=2, block_no=0,
                        prev_hash=None, epoch_nonce=eta)
    db.add_block(late)
    assert db.tip_point().hash_ == late.hash_
    # same length, LOWER slot => preferred under the modified order
    # (Praos would keep `late` — same length means no switch)
    db.add_block(early)
    assert db.tip_point().hash_ == early.hash_


def test_watcher_fires_on_changes_only():
    """Util/STM.hs Watcher: one callback per VALUE CHANGE, none for
    wakeups that observe the same value."""
    from ouroboros_consensus_tpu.utils.registry import watcher
    from ouroboros_consensus_tpu.utils.sim import Event, Fire, Sleep

    sim = Sim()
    ev = Event("watched")
    box = {"v": 0}
    seen = []

    def mutator():
        for v in (1, 1, 2, 2, 3):  # repeated writes of the same value
            box["v"] = v
            yield Fire(ev)
            yield Sleep(0.1)

    reg = ResourceRegistry(sim)
    reg.fork_linked(
        watcher(lambda: box["v"], seen.append, ev, initial=0), "watch"
    )
    sim.spawn(mutator(), "mutator")
    sim.run(until=5.0)
    assert seen == [1, 2, 3]
    reg.close()


def test_follower_promptness():
    """FollowerPromptness (storage-test): in decoupled mode a follower's
    event fires within the SAME virtual instant as adoption — servers
    never sit on stale chains (no polling interval in the path)."""
    import tests.test_pipelining as tp
    from ouroboros_consensus_tpu.utils.sim import Sim, Wait

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        from pathlib import Path

        node = tp._mk_node(Path(d), "n")
        db = node.chain_db
        sim = Sim()
        runners = db.start_decoupled(sim)
        for i, r in enumerate(runners):
            sim.spawn(r, f"runner{i}")
        f = db.new_follower()
        blocks = tp._forge_chain(3)
        seen = []

        def consumer():
            while len(seen) < 3:
                ups = f.take_updates()
                for u in ups:
                    if u[0] == "addblock":
                        seen.append((sim.now, u[1].hash_))
                if len(seen) < 3:
                    yield Wait(f.event)

        def producer():
            from ouroboros_consensus_tpu.utils.sim import Sleep

            for b in blocks:
                db.add_block_async(b)
                yield Sleep(1.0)

        sim.spawn(consumer(), "consumer")
        sim.spawn(producer(), "producer")
        sim.run(until=10.0)
        assert [h for _t, h in seen] == [b.hash_ for b in blocks]
        # promptness: delivered at the adoption instant (t=0,1,2), not
        # on some later polling tick
        assert [t for t, _h in seen] == [0.0, 1.0, 2.0]
