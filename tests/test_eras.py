"""Era rule-set tests: Allegra timelocks, Mary script-policy minting,
Alonzo phase-2 scripts (two-phase IsValid + collateral), Babbage
reference inputs / inline datums, Conway governance — and the full
7-era composite with value crossing every translation.

Reference: Shelley/Eras.hs:85-97 (the era family), Cardano/
CanHardFork.hs:273 (pairwise translations), cardano-ledger's Allegra
Timelock / Alonzo UTXOS / Babbage UTXOW / Conway GOV rule families.
"""

import dataclasses
from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.ledger import allegra, alonzo, babbage, conway, mary
from ouroboros_consensus_tpu.ledger.shelley import PParams, ShelleyGenesis
from ouroboros_consensus_tpu.ops.host import ed25519 as hed
from ouroboros_consensus_tpu.utils import cbor

SEED = b"\x11" * 32
VK = hed.secret_to_public(SEED)
GEN = ShelleyGenesis(
    pparams=PParams(min_fee_a=0, min_fee_b=0), epoch_length=100,
    stability_window=30,
)


def fresh_view(led, st, src_view=None, slot=5):
    v = led.mempool_view(st, slot)
    if src_view is not None:
        v.utxo = dict(src_view.utxo)
    return v


# ---------------------------------------------------------------------------
# Allegra
# ---------------------------------------------------------------------------


class TestAllegraTimelocks:
    def _locked(self):
        led = allegra.AllegraLedger(GEN)
        st = led.genesis_state([(b"payme", None, 1000)])
        lock = allegra.require_all_of([
            allegra.require_signature(VK), allegra.require_time_start(10),
        ])
        v = led.mempool_view(st, 5)
        tx = allegra.encode_tx(
            [(bytes(32), 0)],
            [(allegra.script_addr(lock), None, 600), (b"payme", None, 400)],
        )
        v = led.apply_tx(v, tx)
        return led, st, v, lock, allegra.tx_id(tx)

    def test_witnessed_spend_inside_interval(self):
        led, st, v, lock, tid = self._locked()
        spend = allegra.encode_tx(
            [(tid, 0)], [(b"payme", None, 600)], validity=(12, None),
            scripts=[lock], signers=[SEED],
        )
        vv = fresh_view(led, st, v, slot=15)
        vv = led.apply_tx(vv, spend)
        assert (allegra.tx_id(spend), 0) in vv.utxo

    def test_unwitnessed_spend_rejected(self):
        led, st, v, lock, tid = self._locked()
        spend = allegra.encode_tx(
            [(tid, 0)], [(b"payme", None, 600)], validity=(12, None),
            scripts=[lock],
        )
        with pytest.raises(allegra.ScriptError):
            led.apply_tx(fresh_view(led, st, v, slot=15), spend)

    def test_missing_script_witness_rejected(self):
        led, st, v, lock, tid = self._locked()
        spend = allegra.encode_tx(
            [(tid, 0)], [(b"payme", None, 600)], validity=(12, None),
            signers=[SEED],
        )
        with pytest.raises(allegra.MissingWitness):
            led.apply_tx(fresh_view(led, st, v, slot=15), spend)

    def test_interval_not_proving_time_start_rejected(self):
        # RequireTimeStart needs the interval's LOWER bound >= lock slot
        # — an open interval proves nothing (evalTimelock semantics)
        led, st, v, lock, tid = self._locked()
        spend = allegra.encode_tx(
            [(tid, 0)], [(b"payme", None, 600)], validity=(None, None),
            scripts=[lock], signers=[SEED],
        )
        with pytest.raises(allegra.ScriptError):
            led.apply_tx(fresh_view(led, st, v, slot=15), spend)

    def test_m_of_n_and_time_expire(self):
        led = allegra.AllegraLedger(GEN)
        st = led.genesis_state([(b"payme", None, 100)])
        seeds = [bytes([i]) * 32 for i in (1, 2, 3)]
        vks = [hed.secret_to_public(s) for s in seeds]
        lock = allegra.require_m_of(
            2, [allegra.require_signature(k) for k in vks]
        )
        v = led.mempool_view(st, 5)
        v = led.apply_tx(v, allegra.encode_tx(
            [(bytes(32), 0)], [(allegra.script_addr(lock), None, 100)],
        ))
        tid = allegra.tx_id(allegra.encode_tx(
            [(bytes(32), 0)], [(allegra.script_addr(lock), None, 100)],
        ))
        ok = allegra.encode_tx(
            [(tid, 0)], [(b"payme", None, 100)],
            scripts=[lock], signers=seeds[:2],
        )
        vv = fresh_view(led, st, v)
        vv = led.apply_tx(vv, ok)
        bad = allegra.encode_tx(
            [(tid, 0)], [(b"payme", None, 100)],
            scripts=[lock], signers=seeds[:1],
        )
        with pytest.raises(allegra.ScriptError):
            led.apply_tx(fresh_view(led, st, v), bad)

    def test_bad_key_witness_rejected(self):
        led, st, v, lock, tid = self._locked()
        good = allegra.encode_tx(
            [(tid, 0)], [(b"payme", None, 600)], validity=(12, None),
            scripts=[lock], signers=[SEED],
        )
        fields = cbor.decode(good)
        vk, sig = fields[7][0]
        fields[7][0] = [vk, sig[:-1] + bytes([sig[-1] ^ 1])]
        with pytest.raises(allegra.MissingWitness):
            led.apply_tx(fresh_view(led, st, v, slot=15),
                         cbor.encode(fields))

    def test_malformed_script_is_invalid_tx(self):
        led, st, v, lock, tid = self._locked()
        # a script whose bytes hash to the lock address can't exist;
        # instead attach garbage for a GARBAGE-locked output
        garbage = b"\xff\x01"
        gaddr = allegra.script_addr(garbage)
        v2 = fresh_view(led, st, v, slot=15)
        lock_tx = allegra.encode_tx(
            [(tid, 0)], [(gaddr, None, 600)], validity=(12, None),
            scripts=[lock], signers=[SEED],
        )
        v2 = led.apply_tx(v2, lock_tx)
        spend = allegra.encode_tx(
            [(allegra.tx_id(lock_tx), 0)], [(b"payme", None, 600)],
            scripts=[garbage],
        )
        with pytest.raises(allegra.ScriptError):
            led.apply_tx(fresh_view(led, st, v2, slot=15), spend)


# ---------------------------------------------------------------------------
# Mary (script policies; classic behavior is covered by test_mary.py)
# ---------------------------------------------------------------------------


class TestMaryScriptPolicy:
    def test_timelock_policy_mint(self):
        led = mary.MaryLedger(GEN)
        st = led.translate_from_shelley(
            led.genesis_state([(b"payme", None, 1000)])
        )
        policy = allegra.require_signature(VK)
        pid = allegra.script_hash(policy)
        v = led.mempool_view(st, 5)
        tx = mary.encode_tx(
            [(bytes(32), 0)],
            [(b"payme", None, mary.MaryValue(1000, {(pid, b"TOK"): 7}))],
            mint=[(policy, None, {b"TOK": 7})],
            scripts=[policy], signers=[SEED],
        )
        v = led.apply_tx(v, tx)
        assert v.utxo[(mary.tx_id(tx), 0)][1].asset_map() == {
            (pid, b"TOK"): 7
        }

    def test_timelock_policy_unwitnessed_rejected(self):
        led = mary.MaryLedger(GEN)
        st = led.translate_from_shelley(
            led.genesis_state([(b"payme", None, 1000)])
        )
        policy = allegra.require_signature(VK)
        pid = allegra.script_hash(policy)
        tx = mary.encode_tx(
            [(bytes(32), 0)],
            [(b"payme", None, mary.MaryValue(1000, {(pid, b"TOK"): 7}))],
            mint=[(policy, None, {b"TOK": 7})],
            scripts=[policy],  # no signer -> RequireSignature fails
        )
        with pytest.raises(mary.MintError):
            led.apply_tx(led.mempool_view(st, 5), tx)


# ---------------------------------------------------------------------------
# Alonzo
# ---------------------------------------------------------------------------


class TestAlonzoPhase2:
    SCRIPT = alonzo.plutus_script([4, [1], [2]])  # redeemer == datum
    DATUM = cbor.encode(b"SECRET")

    def _locked(self):
        led = alonzo.AlonzoLedger(GEN)
        st = led.translate_from_mary(
            led.genesis_state([(b"payme", None, 1000)])
        )
        assert isinstance(st.pparams, alonzo.AlonzoPParams)
        v = led.mempool_view(st, 5)
        tx = alonzo.encode_tx(
            [(bytes(32), 0)],
            [(allegra.script_addr(self.SCRIPT), None, 700,
              alonzo.datum_hash(self.DATUM)),
             (b"payme", None, 300)],
        )
        v = led.apply_tx(v, tx)
        return led, st, v, alonzo.tx_id(tx)

    def _spend(self, tid, redeemer, is_valid=True, budget=100, fee=1):
        return alonzo.encode_tx(
            [(tid, 0)], [(b"payme", None, 700 - fee)],
            collateral=[(tid, 1)], scripts=[self.SCRIPT],
            datums=[self.DATUM], redeemers=[(0, 0, redeemer)],
            budget=budget, fee=fee, is_valid=is_valid,
        )

    def test_phase2_success(self):
        led, st, v, tid = self._locked()
        vv = fresh_view(led, st, v, slot=6)
        vv = led.apply_tx(vv, self._spend(tid, cbor.decode(self.DATUM)))
        assert (tid, 0) not in vv.utxo
        assert (tid, 1) in vv.utxo  # collateral untouched on success

    def test_phase2_failure_consumes_collateral_only(self):
        led, st, v, tid = self._locked()
        vv = fresh_view(led, st, v, slot=6)
        vv = led.apply_tx(vv, self._spend(tid, b"WRONG", is_valid=False))
        assert (tid, 0) in vv.utxo  # script utxo survives
        assert (tid, 1) not in vv.utxo  # collateral consumed
        assert vv.fee_delta == 300

    def test_isvalid_lie_rejected(self):
        led, st, v, tid = self._locked()
        with pytest.raises(alonzo.IsValidMismatch):
            led.apply_tx(fresh_view(led, st, v, slot=6),
                         self._spend(tid, b"WRONG", is_valid=True))
        # the converse lie too: claiming invalid when the script passes
        with pytest.raises(alonzo.IsValidMismatch):
            led.apply_tx(
                fresh_view(led, st, v, slot=6),
                self._spend(tid, cbor.decode(self.DATUM), is_valid=False),
            )

    def test_budget_exceeded_is_phase2_failure(self):
        led, st, v, tid = self._locked()
        vv = fresh_view(led, st, v, slot=6)
        # budget 1: the eq node alone costs 3 (eq + two leaves)
        vv = led.apply_tx(
            vv,
            self._spend(tid, cbor.decode(self.DATUM), is_valid=False,
                        budget=1),
        )
        assert (tid, 1) not in vv.utxo

    def test_fee_must_cover_exunits(self):
        led, st, v, tid = self._locked()
        from ouroboros_consensus_tpu.ledger.shelley import FeeTooSmall

        with pytest.raises(FeeTooSmall):
            led.apply_tx(
                fresh_view(led, st, v, slot=6),
                self._spend(tid, cbor.decode(self.DATUM), fee=0),
            )

    def test_missing_datum_and_redeemer_are_phase1_errors(self):
        led, st, v, tid = self._locked()
        no_datum = alonzo.encode_tx(
            [(tid, 0)], [(b"payme", None, 699)], collateral=[(tid, 1)],
            scripts=[self.SCRIPT],
            redeemers=[(0, 0, cbor.decode(self.DATUM))], budget=100, fee=1,
        )
        with pytest.raises(allegra.MissingWitness):
            led.apply_tx(fresh_view(led, st, v, slot=6), no_datum)
        no_redeemer = alonzo.encode_tx(
            [(tid, 0)], [(b"payme", None, 699)], collateral=[(tid, 1)],
            scripts=[self.SCRIPT], datums=[self.DATUM], budget=100, fee=1,
        )
        with pytest.raises(allegra.MissingWitness):
            led.apply_tx(fresh_view(led, st, v, slot=6), no_redeemer)

    def test_collateral_required_and_key_locked(self):
        led, st, v, tid = self._locked()
        no_coll = alonzo.encode_tx(
            [(tid, 0)], [(b"payme", None, 699)],
            scripts=[self.SCRIPT], datums=[self.DATUM],
            redeemers=[(0, 0, cbor.decode(self.DATUM))], budget=100, fee=1,
        )
        with pytest.raises(alonzo.CollateralError):
            led.apply_tx(fresh_view(led, st, v, slot=6), no_coll)
        script_coll = alonzo.encode_tx(
            [(tid, 0)], [(b"payme", None, 699)], collateral=[(tid, 0)],
            scripts=[self.SCRIPT], datums=[self.DATUM],
            redeemers=[(0, 0, cbor.decode(self.DATUM))], budget=100, fee=1,
        )
        with pytest.raises(alonzo.CollateralError):
            led.apply_tx(fresh_view(led, st, v, slot=6), script_coll)

    def test_signed_by_context(self):
        # a script gating on the signatory set: [12, keyhash]
        led = alonzo.AlonzoLedger(GEN)
        st = led.translate_from_mary(
            led.genesis_state([(b"payme", None, 1000)])
        )
        script = alonzo.plutus_script([12, allegra.key_hash(VK)])
        v = led.mempool_view(st, 5)
        lock = alonzo.encode_tx(
            [(bytes(32), 0)],
            [(allegra.script_addr(script), None, 500,
              alonzo.datum_hash(self.DATUM)), (b"payme", None, 500)],
        )
        v = led.apply_tx(v, lock)
        tid = alonzo.tx_id(lock)
        spend = alonzo.encode_tx(
            [(tid, 0)], [(b"payme", None, 499)], collateral=[(tid, 1)],
            scripts=[script], datums=[self.DATUM],
            redeemers=[(0, 0, 0)], budget=100, fee=1, signers=[SEED],
        )
        vv = fresh_view(led, st, v, slot=6)
        vv = led.apply_tx(vv, spend)
        assert (tid, 0) not in vv.utxo


# ---------------------------------------------------------------------------
# Babbage
# ---------------------------------------------------------------------------


class TestBabbage:
    SCRIPT = alonzo.plutus_script([4, [1], [2]])
    DATUM = cbor.encode(b"SECRET")

    def _setup(self):
        led = babbage.BabbageLedger(GEN)
        st = led.translate_from_alonzo(
            led.genesis_state([(b"payme", None, 1000)])
        )
        v = led.mempool_view(st, 5)
        lock = babbage.encode_tx(
            [(bytes(32), 0)],
            [
                (allegra.script_addr(self.SCRIPT), None, 500,
                 ("inline", self.DATUM)),
                (b"payme", None, 300, None, self.SCRIPT),  # ref script
                (b"payme", None, 200),
            ],
        )
        v = led.apply_tx(v, lock)
        return led, st, v, alonzo.tx_id(lock)

    def test_reference_script_and_inline_datum(self):
        led, st, v, tid = self._setup()
        spend = babbage.encode_tx(
            [(tid, 0)], [(b"payme", None, 499)],
            ref_ins=[(tid, 1)], collateral=[(tid, 2)],
            redeemers=[(0, 0, cbor.decode(self.DATUM))], budget=100, fee=1,
        )
        vv = fresh_view(led, st, v, slot=6)
        vv = led.apply_tx(vv, spend)
        assert (alonzo.tx_id(spend), 0) in vv.utxo
        assert (tid, 1) in vv.utxo  # reference input NOT spent

    def test_input_cannot_be_both_spent_and_referenced(self):
        led, st, v, tid = self._setup()
        from ouroboros_consensus_tpu.ledger.shelley import ShelleyTxError

        bad = babbage.encode_tx(
            [(tid, 2)], [(b"payme", None, 200)], ref_ins=[(tid, 2)],
        )
        with pytest.raises(ShelleyTxError):
            led.apply_tx(fresh_view(led, st, v, slot=6), bad)

    def test_collateral_return(self):
        led, st, v, tid = self._setup()
        spend = babbage.encode_tx(
            [(tid, 0)], [(b"payme", None, 499)],
            ref_ins=[(tid, 1)], collateral=[(tid, 2)],
            coll_return=(b"payme", None, 150), total_collateral=50,
            redeemers=[(0, 0, b"WRONG")], budget=100, fee=1,
            is_valid=False,
        )
        vv = fresh_view(led, st, v, slot=6)
        vv = led.apply_tx(vv, spend)
        assert (tid, 2) not in vv.utxo  # collateral consumed
        assert vv.fee_delta == 50  # only total_collateral burned
        # change landed at index |outs|
        ret = [k for k, (a, val) in vv.utxo.items() if int(val) == 150]
        assert ret and ret[0][1] == 1


# ---------------------------------------------------------------------------
# Conway
# ---------------------------------------------------------------------------


SC = b"stakecred-28-bytes-xxxxxxxxx"
DREP = b"drep-cred-28-bytes-xxxxxxxxx"


class TestConwayGovernance:
    def _setup(self):
        led = conway.ConwayLedger(GEN)
        base = led.genesis_state([(b"payme", SC, 10_000)])
        base = dataclasses.replace(
            base, stake_creds={SC: 0}, rewards={SC: 0},
        )
        st = led.translate_from_babbage(base)
        assert isinstance(st, conway.ConwayState)
        assert isinstance(st.pparams, conway.ConwayPParams)
        return led, st

    def test_ppup_and_mir_removed(self):
        led, st = self._setup()
        v = led.mempool_view(st, 5)
        with pytest.raises(conway.GovError):
            led.apply_tx(v, conway.encode_tx(
                [(bytes(32), 0)], [(b"payme", SC, 10_000)],
                certs=[[5, b"x" * 32, {b"min_fee_a": 9}]],
            ))
        with pytest.raises(conway.GovError):
            led.apply_tx(v, conway.encode_tx(
                [(bytes(32), 0)], [(b"payme", SC, 10_000)],
                certs=[[6, 0, b"x" * 32, [[SC, 5]]]],
            ))

    def test_full_governance_cycle_ratifies(self):
        led, st = self._setup()
        v = led.mempool_view(st, 5)
        dep = st.pparams.drep_deposit
        tx1 = conway.encode_tx(
            [(bytes(32), 0)], [(b"payme", SC, 10_000 - dep)],
            certs=[[7, DREP], [9, SC, DREP]],
        )
        v = led.apply_tx(v, tx1)
        tid1 = conway.tx_id(tx1)
        gdep = st.pparams.gov_action_deposit
        tx2 = conway.encode_tx(
            [(tid1, 0)], [(b"payme", SC, 10_000 - dep - gdep)],
            proposals=[(SC, [0, {b"min_fee_a": 7}])],
        )
        v = led.apply_tx(v, tx2)
        tid2 = conway.tx_id(tx2)
        tx3 = conway.encode_tx(
            [(tid2, 0)], [(b"payme", SC, 10_000 - dep - gdep)],
            votes=[(DREP, tid2, 0, True)],
        )
        v = led.apply_tx(v, tx3)
        st2 = led._commit_block_view(st, v, 5)
        t = led.tick(st2, 105)  # cross the boundary
        assert t.state.pparams.min_fee_a == 7
        assert not t.state.gov_actions
        assert t.state.rewards[SC] >= gdep  # deposit refunded

    def test_unvoted_action_expires_with_refund(self):
        led, st = self._setup()
        v = led.mempool_view(st, 5)
        gdep = st.pparams.gov_action_deposit
        tx = conway.encode_tx(
            [(bytes(32), 0)], [(b"payme", SC, 10_000 - gdep)],
            proposals=[(SC, [0, {b"min_fee_a": 7}])],
        )
        v = led.apply_tx(v, tx)
        st2 = led._commit_block_view(st, v, 5)
        lifetime = st.pparams.gov_action_lifetime
        t = led.tick(st2, (lifetime + 2) * 100 + 5)
        assert t.state.pparams.min_fee_a == 0  # NOT adopted
        assert not t.state.gov_actions  # expired
        assert t.state.rewards[SC] >= gdep  # refunded

    def test_reapply_vote_then_dereg_in_same_block(self):
        """REAPPLY must replay a block where a DRep votes and then
        deregisters in a LATER tx of the SAME block: the vote replay
        runs against the post-block state, where the DRep is already
        gone — reapply skips all checks (Extended.hs:159), so this must
        reproduce the applied state, not raise (round-5 review
        finding)."""
        led, st = self._setup()
        dep = st.pparams.drep_deposit
        gdep = st.pparams.gov_action_deposit
        tx1 = conway.encode_tx(
            [(bytes(32), 0)], [(b"payme", SC, 10_000 - dep - gdep)],
            certs=[[7, DREP], [9, SC, DREP]],
            proposals=[(SC, [0, {b"min_fee_a": 7}])],
        )
        tid1 = conway.tx_id(tx1)
        tx2 = conway.encode_tx(
            [(tid1, 0)], [(b"payme", SC, 10_000 - dep - gdep)],
            votes=[(DREP, tid1, 0, True)],
        )
        tx3 = conway.encode_tx(
            [(conway.tx_id(tx2), 0)], [(b"payme", SC, 10_000 - gdep)],
            certs=[[8, DREP]],  # deregister the voter
        )
        class _Blk:
            slot = 5
            txs = (tx1, tx2, tx3)

        blk = _Blk()
        applied = led.apply_block(led.tick(st, 5), blk)
        assert DREP not in applied.dreps
        assert applied.gov_votes  # the vote was recorded before dereg
        reapplied = led.reapply_block(led.tick(st, 5), blk)
        assert reapplied.gov_votes == applied.gov_votes
        assert reapplied.gov_actions == applied.gov_actions
        assert reapplied.deposits == applied.deposits
        assert dict(reapplied.utxo) == dict(applied.utxo)

    def test_vote_from_unregistered_drep_rejected(self):
        led, st = self._setup()
        v = led.mempool_view(st, 5)
        gdep = st.pparams.gov_action_deposit
        tx = conway.encode_tx(
            [(bytes(32), 0)], [(b"payme", SC, 10_000 - gdep)],
            proposals=[(SC, [0, {b"min_fee_a": 7}])],
        )
        v = led.apply_tx(v, tx)
        bad = conway.encode_tx(
            [(conway.tx_id(tx), 0)], [(b"payme", SC, 10_000 - gdep)],
            votes=[(DREP, conway.tx_id(tx), 0, True)],
        )
        with pytest.raises(conway.GovError):
            led.apply_tx(v, bad)


# ---------------------------------------------------------------------------
# The 7-era composite
# ---------------------------------------------------------------------------


def test_seven_era_composite(tmp_path):
    """byron → shelley → allegra → mary → alonzo → babbage → conway:
    value (and the minted asset) crosses every translation; the alonzo
    segment runs a live phase-2 script spend; conway registers a DRep
    and runs a governance action through proposal, vote and expiry."""
    from ouroboros_consensus_tpu.hardfork import composite as C
    from ouroboros_consensus_tpu.ledger.conway import ConwayState
    from ouroboros_consensus_tpu.ledger.mary import MaryValue

    cfg = C.CardanoMockConfig(
        byron_epochs=2, byron_epoch_length=40, epoch_length=40,
        seven_era=True, era_epochs=1, with_ledgers=True,
        shelley_f=Fraction(1), babbage_f=Fraction(1), k=5, kes_depth=3,
    )
    path = str(tmp_path / "chain")
    n = C.synthesize(path, cfg, n_slots=360)
    assert n == 360
    res = C.revalidate(path, cfg, backend="host")
    assert res.error is None
    assert res.n_valid == res.n_blocks == 360
    assert set(res.per_era) == {
        "byron", "shelley", "allegra", "mary", "alonzo", "babbage",
        "conway",
    }
    inner = res.final_ledger_state.inner
    assert isinstance(inner, ConwayState)
    assert inner.dreps  # the composite's DRep registration survived
    carried = [
        v for _a, v in inner.utxo.values()
        if isinstance(v, MaryValue) and v.assets
    ]
    assert carried, "the minted asset must survive five translations"
