"""tools e2e: synthesize a chain, then analyse it — the `tools-test`
analog (reference: test/tools-test/Main.hs — db-synthesizer forge by
slot/block limit, then db-analyser CountBlocks + validation over the
same on-disk DB)."""

from fractions import Fraction

import pytest

from ouroboros_consensus_tpu.protocol import praos
from ouroboros_consensus_tpu.tools import db_analyser, db_synthesizer
from ouroboros_consensus_tpu.testing import fixtures

PARAMS = praos.PraosParams(
    slots_per_kes_period=100,
    max_kes_evolutions=62,
    security_param=4,
    active_slot_coeff=Fraction(1, 2),
    epoch_length=50,
    kes_depth=3,
)


@pytest.fixture(scope="module")
def pools():
    return [fixtures.make_pool(i, kes_depth=PARAMS.kes_depth) for i in range(2)]


@pytest.fixture(scope="module")
def lview(pools):
    return fixtures.make_ledger_view(pools)


@pytest.fixture(scope="module")
def synth_db(tmp_path_factory, pools, lview):
    path = str(tmp_path_factory.mktemp("synthdb"))
    res = db_synthesizer.synthesize(
        path,
        PARAMS,
        pools,
        lview,
        db_synthesizer.ForgeLimit(slots=120),  # crosses epochs at 50 and 100
        chunk_size=32,  # small chunks: exercise multi-chunk streaming
    )
    assert res.n_slots == 120
    assert res.n_blocks > 30  # f=1/2, 2 pools: ~>half the slots forge
    return path, res


def test_count_blocks(synth_db):
    path, res = synth_db
    assert db_analyser.count_blocks(path) == res.n_blocks


def test_host_revalidation(synth_db, lview):
    path, res = synth_db
    out = db_analyser.revalidate(path, PARAMS, lview, backend="host")
    assert out.error is None
    assert out.n_valid == res.n_blocks
    # final protocol state matches what the forging loop threaded
    assert out.final_state.evolving_nonce == res.final_state.evolving_nonce
    assert out.final_state.epoch_nonce == res.final_state.epoch_nonce


@pytest.mark.slow
def test_device_revalidation_matches_host(synth_db, lview):
    path, res = synth_db
    host = db_analyser.revalidate(path, PARAMS, lview, backend="host")
    dev = db_analyser.revalidate(path, PARAMS, lview, backend="device")
    assert dev.error is None
    assert dev.n_valid == host.n_valid == res.n_blocks
    assert dev.final_state == host.final_state


def test_corrupt_block_detected(synth_db, lview, tmp_path):
    """--only-validation on a corrupted DB: integrity check truncates or
    validation reports the bad block (ImmutableDB/Impl/Validation.hs:67)."""
    import os
    import shutil

    path, res = synth_db
    cpath = str(tmp_path / "corrupt")
    shutil.copytree(path, cpath)
    # flip a byte mid-way through the first chunk file's block region
    immdir = os.path.join(cpath, "immutable")
    chunk = sorted(f for f in os.listdir(immdir) if f.endswith(".chunk"))[0]
    fp = os.path.join(immdir, chunk)
    data = bytearray(open(fp, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(fp, "wb").write(bytes(data))
    out = db_analyser.revalidate(cpath, PARAMS, lview, backend="host")
    # either the startup integrity pass truncated the tail, or header
    # validation caught the corruption — both are acceptable reference
    # behaviors (truncate-corrupted-tail, Impl/Validation.hs)
    assert out.n_valid < res.n_blocks or out.error is not None


def test_benchmark_ledger_ops_csv(synth_db, lview, tmp_path):
    path, res = synth_db
    csv = str(tmp_path / "ops.csv")
    rows = db_analyser.benchmark_ledger_ops(path, PARAMS, lview, out_csv=csv)
    assert len(rows) == res.n_blocks
    lines = open(csv).read().strip().splitlines()
    assert lines[0].startswith("slot,block_no")
    assert len(lines) == res.n_blocks + 1


def test_config_roundtrip_and_cli_pipeline(tmp_path, pools, lview):
    """Node config + genesis JSON (tools/Cardano/Node/ analog): the
    synthesizer CLI emits config files with the chain; load_config
    restores identical params/view/credentials; the analyser CLI picks
    the config up implicitly — the reference's tools-test pipeline over
    its disk/config/config.json fixture."""
    from ouroboros_consensus_tpu.tools import config as node_config

    cpath = node_config.write_genesis_files(
        str(tmp_path / "config"), PARAMS, lview, pools
    )
    params2, lview2, pools2 = node_config.load_config(cpath)
    assert params2 == PARAMS
    assert lview2.pool_distr == lview.pool_distr
    assert pools2 == pools

    # full CLI pipeline: synthesize --config -> analyse (implicit config)
    out = str(tmp_path / "chain")
    db_synthesizer.main([
        "--out", out, "--blocks", "8", "--config", cpath,
    ])
    db_analyser.main([
        "--db", out, "--analysis", "only-validation", "--backend", "host",
    ])
    assert db_analyser.count_blocks(out) == 8


def test_tutorial_runs():
    """The tutorials (reference src/tutorials/Tutorial/{Simple,WithEpoch}.lhs
    analog) must stay runnable."""
    import subprocess
    import sys

    for script in ("tutorials/simple_protocol.py", "tutorials/shelley_node.py",
                   "tutorials/cardano_node.py"):
        r = subprocess.run(
            [sys.executable, script],
            capture_output=True, text=True, timeout=240,
        )
        assert r.returncode == 0, (script, r.stderr)
        assert "tutorial complete" in r.stdout, script


def test_show_block_stats(synth_db):
    path, res = synth_db
    stats = db_analyser.show_block_stats(path)
    assert stats["n_blocks"] == res.n_blocks
    assert stats["min_block_bytes"] > 0
    assert stats["last_slot"] < 120


def _valid_tx_chain(tmp_path):
    """A chain whose bodies are VALID mock-ledger txs (each block spends
    a distinct genesis output)."""
    from fractions import Fraction as F

    from ouroboros_consensus_tpu.block import forge_block
    from ouroboros_consensus_tpu.ledger import mock as mock_ledger
    from ouroboros_consensus_tpu.ledger.mock import encode_tx
    from ouroboros_consensus_tpu.storage.immutable import ImmutableDB

    ledger = mock_ledger.MockLedger(
        mock_ledger.MockConfig(None, PARAMS.stability_window)
    )
    genesis = ledger.genesis_state([(b"a%d" % i, 5) for i in range(8)])
    pool = fixtures.make_pool(0, kes_depth=PARAMS.kes_depth)
    path = str(tmp_path / "txchain")
    imm = ImmutableDB(path + "/immutable", chunk_size=100)
    prev = None
    for i in range(6):
        tx = encode_tx([(bytes(32), i)], [(b"out%d" % i, 5)])
        b = forge_block(
            PARAMS, pool, slot=i + 1, block_no=i, prev_hash=prev,
            epoch_nonce=b"\x22" * 32, txs=(tx,),
        )
        imm.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
        prev = b.hash_
    lview2 = fixtures.make_ledger_view([pool])
    return path, ledger, genesis, lview2


def test_store_ledger_state_at_and_repro_mempool(tmp_path):
    """StoreLedgerStateAt (Analysis.hs:118) + ReproMempoolAndForge
    (Analysis.hs:615) over a chain with real mock-ledger txs."""
    path, ledger, genesis, lview2 = _valid_tx_chain(tmp_path)
    snap_dir = str(tmp_path / "snaps")
    name = db_analyser.store_ledger_state_at(
        path, PARAMS, lview2, slot=4, ledger=ledger,
        genesis_state=genesis, snap_dir=snap_dir,
    )
    assert name == "snapshot-4"
    from ouroboros_consensus_tpu.storage.ledgerdb import decode_snapshot

    ext = decode_snapshot(open(f"{snap_dir}/{name}", "rb").read())
    assert ext.header_state.tip.slot == 4
    # 4 genesis outputs spent by slots 1..4
    assert (bytes(32), 0) not in ext.ledger_state.utxo
    assert (bytes(32), 5) in ext.ledger_state.utxo

    rows = db_analyser.repro_mempool_and_forge(path, ledger, genesis)
    assert len(rows) == 6
    assert all(r["accepted"] == 1 and r["rejected"] == 0 for r in rows)
    assert all(r["dur_snap_us"] >= 0 for r in rows)


def test_text_envelope_credentials(tmp_path, pools):
    """Cardano.Api shim: TextEnvelope key files ({type, description,
    cborHex}) roundtrip a pool's signing identity; a wrong type string
    is refused."""
    import json as _json

    from ouroboros_consensus_tpu.tools import config as node_config

    d = str(tmp_path / "creds")
    paths = node_config.write_text_envelopes(d, pools[0])
    assert set(paths) == {"cold", "vrf", "kes"}
    env = _json.load(open(paths["cold"]))
    assert set(env) == {"type", "description", "cborHex"}
    again = node_config.load_pool_from_envelopes(d)
    assert again == pools[0]
    assert again.kes_vk == pools[0].kes_vk
    with pytest.raises(ValueError):
        node_config.read_text_envelope(paths["cold"], "KesSigningKey_compactsum")


def test_check_state_growth(synth_db, lview):
    """CheckNoThunksEvery analog: sampled state sizes over a replay —
    the ocert-counter map must stay bounded by the pool count (a
    per-block accretion would show as a slope)."""
    path, res = synth_db
    samples = db_analyser.check_state_growth_every(
        path, PARAMS, lview, None, None, every=10
    )
    assert len(samples) >= 3
    # bounded by the pool count — and STABLE once both pools have
    # forged: no per-block accretion slope in the second half
    assert all(s["ocert_counters"] <= 2 for s in samples)
    second_half = [s["ocert_counters"] for s in samples[len(samples) // 2:]]
    assert len(set(second_half)) == 1, second_half


def test_show_slot_block_no(synth_db, capsys):
    """ShowSlotBlockNo (Analysis.hs:76): one line per block, monotone
    slots, block numbers 0..n-1."""
    path, res = synth_db
    lines = []
    n = db_analyser.show_slot_block_no(path, out=lines.append)
    assert n == res.n_blocks == len(lines)
    slots = [int(l.split("slot: ")[1].split(",")[0]) for l in lines]
    bnos = [int(l.split("blockNo: ")[1]) for l in lines]
    assert slots == sorted(slots)
    assert bnos == list(range(res.n_blocks))


def test_count_tx_outputs(tmp_path):
    """CountTxOutputs (Analysis.hs:77) over a chain with real mock txs:
    each of the 6 blocks carries one tx with one output."""
    path, ledger, genesis, lview2 = _valid_tx_chain(tmp_path)
    assert db_analyser.count_tx_outputs(path) == 6


def test_show_block_header_size(synth_db):
    """ShowBlockHeaderSize (Analysis.hs:78): one row per block, max is
    the maximum of the per-block sizes and matches the real encoding."""
    path, res = synth_db
    lines = []
    max_size = db_analyser.show_block_header_size(path, out=lines.append)
    assert lines[-1] == f"maxHeaderSize: {max_size}"
    sizes = [int(l.split("headerSize: ")[1]) for l in lines[:-1]]
    assert len(sizes) == res.n_blocks
    assert max(sizes) == max_size > 0


def test_show_block_txs_size(tmp_path):
    """ShowBlockTxsSize (Analysis.hs:79): per-block tx sizes over a
    chain with real mock txs sum to the returned totals."""
    path, ledger, genesis, lview2 = _valid_tx_chain(tmp_path)
    lines = []
    n, total = db_analyser.show_block_txs_size(path, out=lines.append)
    assert n == 6
    per_block = [int(l.split("blockTxsSize: ")[1]) for l in lines[:-1]]
    assert sum(per_block) == total > 0


def test_show_ebbs_none_on_praos_chain(synth_db):
    """ShowEBBs (Analysis.hs:81): a pure-Praos chain has no EBBs."""
    path, _res = synth_db
    assert db_analyser.show_ebbs(path) == []


def test_show_ebbs_finds_byron_mock_ebbs(tmp_path):
    """ShowEBBs on a ByronMock-era chain that starts with a real EBB."""
    from ouroboros_consensus_tpu.hardfork import byron_mock as bm
    from ouroboros_consensus_tpu.storage.immutable import ImmutableDB

    path = str(tmp_path / "byron")
    imm = ImmutableDB(path + "/immutable", chunk_size=100)
    ebb = bm.forge_ebb(slot=0, block_no=0, prev_hash=None)
    imm.append_block(ebb.slot, ebb.block_no, ebb.hash_, ebb.bytes_)
    b = bm.forge_block(
        b"seed-0" * 6, slot=1, block_no=1, prev_hash=ebb.hash_,
    )
    imm.append_block(b.slot, b.block_no, b.hash_, b.bytes_)
    rows = db_analyser.show_ebbs(path, decode_block=bm.ByronMockBlock.from_bytes)
    assert len(rows) == 1
    assert rows[0]["slot"] == 0 and rows[0]["known"]


def test_trace_ledger_processing(tmp_path):
    """TraceLedgerProcessing (Analysis.hs:80): InspectLedger events are
    surfaced per transition during replay."""
    path, ledger, genesis, lview2 = _valid_tx_chain(tmp_path)

    class InspectingLedger:
        """Wraps the mock ledger with an inspect() that reports UTxO
        growth — a stand-in for the HFC's era-transition events."""

        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, k):
            return getattr(self._inner, k)

        def inspect(self, old, new):
            from ouroboros_consensus_tpu.ledger.inspect import LedgerUpdate

            if len(new.utxo) != len(old.utxo):
                return [LedgerUpdate(f"utxo {len(old.utxo)}->{len(new.utxo)}")]
            return []

    events = db_analyser.trace_ledger_processing(
        path, PARAMS, lview2, InspectingLedger(ledger), genesis,
    )
    assert len(events) == 0  # spend 1 + create 1 per block: size constant

    # a ledger whose inspect always fires sees every block
    class Chatty(InspectingLedger):
        def inspect(self, old, new):
            from ouroboros_consensus_tpu.ledger.inspect import LedgerUpdate

            return [LedgerUpdate("tick")]

    events = db_analyser.trace_ledger_processing(
        path, PARAMS, lview2, Chatty(ledger), genesis,
    )
    assert len(events) == 6


def test_shelley_genesis_roundtrip(tmp_path):
    """shelley-genesis.json (sgInitialFunds + sgStaking shape) feeds
    protocolInfoShelley: write -> load -> genesis state identical to
    building it in process, elections included."""
    from fractions import Fraction

    from ouroboros_consensus_tpu.ledger import shelley as sh
    from ouroboros_consensus_tpu.protocol.views import hash_key, hash_vrf_vk
    from ouroboros_consensus_tpu.testing import fixtures
    from ouroboros_consensus_tpu.tools import config as cfg_tools

    pool = fixtures.make_pool(3, kes_depth=2)
    cred = b"g-cred" + b"\x00" * 22
    g = sh.ShelleyGenesis(
        pparams=sh.PParams(min_fee_a=0, min_fee_b=0, key_deposit=5,
                           pool_deposit=9, a0=Fraction(1, 4)),
        epoch_length=100, stability_window=300, max_supply=1_000_000,
        genesis_delegates=(b"GD" + b"\x00" * 26,), update_quorum=1,
    )
    funds = [(b"p" * 28, cred, 777), (b"q" * 28, None, 23)]
    pools = (sh.PoolParams(hash_key(pool.vk_cold), hash_vrf_vk(pool.vrf_vk),
                           1, 2, Fraction(1, 8), cred, (cred,)),)
    delegs = ((cred, hash_key(pool.vk_cold)),)

    path = cfg_tools.write_shelley_genesis(
        str(tmp_path), g, funds, pools, delegs
    )
    ledger, state = cfg_tools.load_shelley_genesis(path)
    direct = sh.ShelleyLedger(g).genesis_state(
        funds, initial_pools=pools, initial_delegations=delegs
    )
    assert ledger.genesis == g
    assert state == direct
    # elections work off the loaded state
    view = ledger.protocol_ledger_view(ledger.tick(state, 1))
    assert view.pool_distr[hash_key(pool.vk_cold)].stake == Fraction(1)
