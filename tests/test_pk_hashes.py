"""Differential tests: ops/pk/hashes vs hashlib (SHA-512, Blake2b)."""

import hashlib

import numpy as np

import jax
from jax import numpy as jnp

from ouroboros_consensus_tpu.ops.pk import hashes as ph

B = 48
rng = np.random.default_rng(11)


def stage(msgs):
    """Equal-length messages -> [n, B] int32."""
    n = len(msgs[0])
    arr = np.zeros((n, len(msgs)), np.int32)
    for i, m in enumerate(msgs):
        arr[:, i] = np.frombuffer(m, np.uint8)
    return jnp.asarray(arr)


def unstage(arr):
    a = np.asarray(arr)
    return [bytes(a[:, i].astype(np.uint8)) for i in range(a.shape[1])]


def test_sha512_fixed_one_and_two_blocks():
    # 66 and 130 are the ECVRF product shapes (hash-to-curve, challenge);
    # more lengths would only re-pay the slow XLA:CPU compile of the
    # unrolled rounds without new coverage
    for n in (66, 130):
        msgs = [rng.bytes(n) for _ in range(B)]
        got = unstage(jax.jit(ph.sha512_fixed)(stage(msgs)))
        want = [hashlib.sha512(m).digest() for m in msgs]
        assert got == want, f"len {n}"


def test_sha512_var_blocks():
    """Per-lane block counts: mixed-length messages, standard padding."""
    from ouroboros_consensus_tpu.ops import sha512 as xs

    lens = [int(rng.integers(1, 300)) for _ in range(B)]
    msgs = [rng.bytes(n) for n in lens]
    blocks, nblocks = xs.pad_messages_np(msgs)  # [B, NB, 16, 2] words
    # convert word blocks back to [NB, 128, B] bytes for the pk layout
    nb = blocks.shape[1]
    byts = np.zeros((nb, 128, B), np.int32)
    for i, m in enumerate(msgs):
        k = xs.nblocks_for_len(len(m))
        padded = bytearray(k * 128)
        padded[: len(m)] = m
        padded[len(m)] = 0x80
        padded[-16:] = (8 * len(m)).to_bytes(16, "big")
        for blk in range(k):
            byts[blk, :, i] = np.frombuffer(bytes(padded[blk * 128 : (blk + 1) * 128]), np.uint8)
    got = unstage(
        jax.jit(ph.sha512_var)(jnp.asarray(byts), jnp.asarray(nblocks))
    )
    want = [hashlib.sha512(m).digest() for m in msgs]
    assert got == want


def test_blake2b_fixed():
    # 64/32 = KES Merkle node; 65/32 = leader/nonce range extension
    for n, ds in ((64, 32), (65, 32)):
        msgs = [rng.bytes(n) for _ in range(B)]
        got = unstage(
            jax.jit(lambda d: ph.blake2b_fixed(d, n, ds))(stage(msgs))
        )
        want = [hashlib.blake2b(m, digest_size=ds).digest() for m in msgs]
        assert got == want, f"len {n} ds {ds}"
