"""North-star benchmark: END-TO-END Praos chain revalidation.

Mirrors the reference's `db-analyser --only-validation` shape
(Tools/DBAnalyser/Run.hs:133-143): open the on-disk ImmutableDB of a
db-synthesizer chain with full integrity checking, stream + parse every
block (native C++ chunk scanner), stage SoA batches, run the fused TPU
kernel (Ed25519 OCert + CompactSum KES + ECVRF + leader threshold +
nonce range extension — Praos.hs:441-606 semantics) with pipelined
host/device overlap, and fold the sequential epilogue. The measured
baseline is the SAME end-to-end replay through the single-core C++
verifier (native/hostcrypto.cpp — the role libsodium plays under the
reference), on the same chain, same process.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "headers/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time
from fractions import Fraction

BENCH_HEADERS = int(os.environ.get("BENCH_HEADERS", "100000"))
KES_DEPTH = int(os.environ.get("BENCH_KES_DEPTH", "7"))
MAX_BATCH = int(os.environ.get("BENCH_MAX_BATCH", "8192"))
CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")


def bench_params():
    """Mainnet-shaped ratios: epoch/k = 20, f = 1/2 (so ~epoch_length/2
    blocks per epoch), several epochs and KES periods over the run —
    nonce rotation, epoch segmentation and KES evolutions all exercised."""
    from ouroboros_consensus_tpu.protocol import praos

    return praos.PraosParams(
        slots_per_kes_period=3600,
        max_kes_evolutions=62,
        security_param=2160,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=43200,
        kes_depth=KES_DEPTH,
    )


def build_or_load_chain():
    """Synthesize (once, cached on disk) a BENCH_HEADERS-block chain."""
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    params = bench_params()
    path = os.path.join(CACHE, f"chain_h{BENCH_HEADERS}_d{KES_DEPTH}")
    pools, lview = synth.make_credentials(1, kes_depth=KES_DEPTH)
    marker = os.path.join(path, "COMPLETE")
    if not os.path.exists(marker):
        import shutil

        shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)
        t0 = time.monotonic()
        res = synth.synthesize(
            path, params, pools, lview,
            synth.ForgeLimit(blocks=BENCH_HEADERS),
            trace=lambda s: print(f"# synth: {s}", file=sys.stderr),
        )
        print(
            f"# synthesized {res.n_blocks} blocks in "
            f"{time.monotonic()-t0:.0f}s",
            file=sys.stderr,
        )
        with open(marker, "w") as f:
            f.write("ok")
    return path, params, lview


def run_replay(path, params, lview, backend: str):
    from ouroboros_consensus_tpu.tools import db_analyser as ana

    t0 = time.monotonic()
    r = ana.revalidate(
        path, params, lview, backend=backend, validate_all=True,
        max_batch=MAX_BATCH,
    )
    wall = time.monotonic() - t0
    assert r.error is None, f"bench chain must revalidate clean: {r.error!r}"
    assert r.n_valid == r.n_blocks > 0
    return r.n_valid, wall, r


def main() -> None:
    import jax

    # honor an explicit platform request even under this box's
    # sitecustomize (which force-prefers the axon TPU plugin after
    # interpreter start, making the env var alone insufficient)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    jax.config.update("jax_compilation_cache_dir", "/tmp/ouroboros-jax-cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    path, params, lview = build_or_load_chain()

    # the TPU tunnel on this box can wedge transiently; ride out a short
    # outage. Probing must happen in FRESH subprocesses: jax caches
    # partially-initialized backend state, so an in-process retry after
    # a failure can silently come back CPU-only. Only when a probe
    # succeeds do we initialize in THIS process (its first init).
    import subprocess

    for attempt in range(5):
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True, timeout=300,
            )
            err = probe.stderr if probe.returncode else None
            if probe.returncode == 0:
                break
        except subprocess.TimeoutExpired:
            err = "probe timed out (backend init hung)"
        print(
            f"# backend probe failed (attempt {attempt + 1}/5): "
            f"{str(err).strip().splitlines()[-1] if err else '?'}",
            file=sys.stderr,
        )
        if attempt < 4:
            time.sleep(60)
    platform = jax.devices()[0].platform

    # warmup: compile the kernel on a small prefix replay
    t0 = time.monotonic()
    n0, w0, _ = run_replay(path, params, lview, "device")
    warm_s = time.monotonic() - t0

    n, best, detail = None, None, None
    for _ in range(2):
        n, wall, r = run_replay(path, params, lview, "device")
        if best is None or wall < best:
            best, detail = wall, r
    rate = n / best

    nb, bwall, _ = run_replay(path, params, lview, "native")
    baseline = nb / bwall

    print(
        f"# platform={platform} headers={n} warmup={warm_s:.1f}s "
        f"best={best:.2f}s (validate {detail.device_s:.2f}s) "
        f"native_baseline={baseline:.0f}/s ({bwall:.1f}s)",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": (
                    "end-to-end db-analyser revalidation of a "
                    f"{n}-header synthetic Praos chain (disk->parse->"
                    "stage->Ed25519+KES+VRF+leader->nonce fold), device "
                    "vs measured single-core C++ (libsodium-class) replay"
                ),
                "value": round(rate, 1),
                "unit": "headers/s",
                "vs_baseline": round(rate / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
