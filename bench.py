"""North-star benchmark: END-TO-END Praos chain revalidation.

Mirrors the reference's `db-analyser --only-validation` shape
(Tools/DBAnalyser/Run.hs:133-143): open the on-disk ImmutableDB of a
db-synthesizer chain with full integrity checking (ValidateAllChunks —
CRC + body-hash walk — folded into the replay's own chunk reads: one
disk pass, same checks/truncation as the reference's open-time policy,
Tools/DBAnalyser.hs:133-136), stream + parse every
block (native C++ chunk scanner), stage SoA batches, run the Pallas TPU
verification kernels (Ed25519 OCert + CompactSum KES + ECVRF + leader
threshold + nonce range extension — Praos.hs:441-606 semantics, ops/pk)
with pipelined host/device overlap, and fold the sequential epilogue.
The measured baseline is the SAME end-to-end replay through the
single-core C++ verifier (native/hostcrypto.cpp — the role libsodium
plays under the reference), on the same chain, same process.

Un-killable by design (round-2 postmortem: the TPU tunnel wedged, the
probe loop had no overall deadline, and the driver recorded rc=124 with
no JSON): every device interaction runs in a SUBPROCESS under a bounded
budget; the native baseline is measured first in-process; and the ONE
JSON line is printed no matter what the tunnel does, with
"device_unavailable": true when the device result is missing.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "headers/s", "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from fractions import Fraction

CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")
KES_DEPTH = int(os.environ.get("BENCH_KES_DEPTH", "7"))


def _default_headers() -> int:
    """The north star is the 1M-header chain (BASELINE.json); replay it
    whenever its synth cache exists. Synthesizing 1M takes ~15 min of
    native forging — too long inside the bench's wall ceiling — so a
    cold cache falls back to the 100k chain (which synthesizes in ~2.5
    min) rather than blowing the budget. scripts/tpu_session.sh and the
    round's own runs build the 1M cache; once present, every later
    bench run measures at full scale."""
    if os.path.exists(
        os.path.join(CACHE_DIR, f"chain_h1000000_d{KES_DEPTH}", "COMPLETE")
    ):
        return 1_000_000
    return 100_000


BENCH_HEADERS = int(os.environ.get("BENCH_HEADERS", "0")) or _default_headers()
MAX_BATCH = int(os.environ.get("BENCH_MAX_BATCH", "8192"))
# total wall budget for device probing (fresh-process trivial op)
PROBE_BUDGET = float(os.environ.get("BENCH_PROBE_BUDGET", "180"))
# total wall budget for the device-side measurement subprocess
DEVICE_BUDGET = float(os.environ.get("BENCH_DEVICE_BUDGET", "1200"))
# overall wall ceiling for the WHOLE bench run: whatever the driver's
# own timeout is, the JSON line must come out before it fires (round 2
# recorded rc=124 around the 20-minute mark — stay well inside that).
# Probing and the device subprocess only get the time that remains
# under this ceiling after synthesis + the native baseline.
TOTAL_BUDGET = float(os.environ.get("BENCH_TOTAL_BUDGET", "1020"))
_T0 = time.monotonic()


def _remaining() -> float:
    return TOTAL_BUDGET - (time.monotonic() - _T0)
CACHE = CACHE_DIR
# per-build jax persistent caches live under .bench_cache/jax-<slug>
# (the child resolves the slug from its runtime build-id and records the
# chosen dir here for the parent's between-attempt wipe)
JAX_CACHE_ROOT = os.path.join(CACHE_DIR, "jax")
JAX_CACHE_PATH_FILE = os.path.join(CACHE_DIR, "jax_cache_path.txt")
# the child's compile/warmup flight-recorder file (obs/warmup.py): every
# stage first-execute / AOT outcome / cache-probe note is flushed here
# atomically, so a child KILLED mid-warmup still leaves a diagnosis the
# round JSON banks as `warmup_report` (the r02-r05 failure mode must
# produce forensics, not silence)
WARMUP_REPORT_PATH = os.path.join(CACHE_DIR, "warmup_report.json")
# the child's live heartbeat (obs/live.py): atomically rewritten every
# ~2 s so the parent (and scripts/tpu_watchdog.sh) can tell compiling /
# staging / running / stalled / dead apart WHILE the child runs — the
# r02-r05 rounds were black boxes until the wall killed them
HEARTBEAT_PATH = os.path.join(CACHE_DIR, "heartbeat.json")
# stall-watchdog no-progress budget for the child (seconds); generous
# against real compile walls — the warmup recorder notes every first
# execute, which COUNTS as progress, so only a genuine wedge trips it
STALL_BUDGET_S = os.environ.get("OCT_STALL_BUDGET_S", "240")


def _warmup_report_path() -> str:
    return os.environ.get("OCT_WARMUP_REPORT") or WARMUP_REPORT_PATH


def _heartbeat_path() -> str:
    return os.environ.get("OCT_HEARTBEAT") or HEARTBEAT_PATH


def _stall_dump_path() -> str:
    # obs/live.stall_dump_path derives "next to the warmup report" in
    # the CHILD; mirror the resolution here so the parent reads the
    # same file the child writes
    explicit = os.environ.get("OCT_STALL_DUMP")
    if explicit:
        return explicit
    return os.path.join(
        os.path.dirname(os.path.abspath(_warmup_report_path())),
        "stall_dump.json",
    )


def _read_warmup_report(path: str | None = None) -> dict | None:
    from ouroboros_consensus_tpu.obs import warmup as _wu

    return _wu.read_report(path or _warmup_report_path())


def bench_params():
    """Mainnet-shaped ratios: epoch/k = 20, f = 1/2 (so ~epoch_length/2
    blocks per epoch), several epochs and KES periods over the run —
    nonce rotation, epoch segmentation and KES evolutions all exercised."""
    from ouroboros_consensus_tpu.protocol import praos

    return praos.PraosParams(
        slots_per_kes_period=3600,
        max_kes_evolutions=62,
        security_param=2160,
        active_slot_coeff=Fraction(1, 2),
        epoch_length=43200,
        kes_depth=KES_DEPTH,
    )


def build_or_load_chain():
    """Synthesize (once, cached on disk) a BENCH_HEADERS-block chain."""
    from ouroboros_consensus_tpu.tools import db_synthesizer as synth

    params = bench_params()
    path = os.path.join(CACHE, f"chain_h{BENCH_HEADERS}_d{KES_DEPTH}")
    pools, lview = synth.make_credentials(1, kes_depth=KES_DEPTH)
    marker = os.path.join(path, "COMPLETE")
    if not os.path.exists(marker):
        import shutil

        shutil.rmtree(path, ignore_errors=True)
        os.makedirs(path, exist_ok=True)
        t0 = time.monotonic()
        res = synth.synthesize(
            path, params, pools, lview,
            synth.ForgeLimit(blocks=BENCH_HEADERS),
            trace=lambda s: print(f"# synth: {s}", file=sys.stderr),
        )
        print(
            f"# synthesized {res.n_blocks} blocks in "
            f"{time.monotonic()-t0:.0f}s",
            file=sys.stderr,
        )
        with open(marker, "w") as f:
            f.write("ok")
    return path, params, lview


# backoff'd RETRIES of a failed backend probe, under their own small
# budget carved out of PROBE_BUDGET: r02-r04 each died on a single probe
# timeout — retries catch the transient-tunnel case without letting a
# dead tunnel eat the measurement wall. Round 12: the fixed 15 s retry
# backoff became JITTERED EXPONENTIAL (15 s, 30 s, 60 s base, x1.0-1.5
# jitter; seeded by OCT_CHAOS_SEED when chaos is armed so recovery
# timing is reproducible), and every attempt's wait is banked in the
# structured verdict — perf_report can tell "backed off and recovered"
# from "retried instantly and died".
PROBE_RETRY_BUDGET = float(os.environ.get("BENCH_PROBE_RETRY_BUDGET", "75"))
PROBE_RETRY_BACKOFF_S = 15.0  # base of the exponential ladder
PROBE_MAX_ATTEMPTS = 4


def _probe_backoff_s(attempt: int) -> float:
    """Jittered exponential wait before retry `attempt` (attempt >= 2):
    base * 2^(attempt-2) * chaos.jitter() — the ONE shared jitter
    policy (uniform [1.0, 1.5); rides the seeded chaos RNG when armed,
    same as the recovery ladder's backoff)."""
    from ouroboros_consensus_tpu.testing import chaos

    return PROBE_RETRY_BACKOFF_S * (2 ** (attempt - 2)) * chaos.jitter()


def probe_device() -> tuple[bool, dict]:
    """Fresh-subprocess backend probe -> (ok, verdict). Attempt 1 runs
    under min(PROBE_BUDGET, remaining wall); failures retry with
    jittered exponential backoff under the separate (shared)
    BENCH_PROBE_RETRY_BUDGET, up to PROBE_MAX_ATTEMPTS total. The
    verdict dict distinguishes probe-timeout (backend init hung) from
    probe-error (backend up, wrong answer) per attempt and records the
    wait that preceded it — it is banked into the round JSON and the
    run ledger so a dead round's tail says WHICH way the probe died
    (and whether backing off ever helped), not just that it did."""
    from ouroboros_consensus_tpu.testing import chaos

    verdict: dict = {"ok": False, "attempts": []}
    # keep at least ~2 min of ceiling for the measurement itself
    budget = min(PROBE_BUDGET, _remaining() - 120)
    if budget <= 5:
        print("# no wall budget left for device probing", file=sys.stderr)
        verdict["outcome"] = "no-budget"
        return False, verdict
    deadline = time.monotonic() + budget
    retry_deadline = None  # armed by the first failure
    for attempt in range(1, PROBE_MAX_ATTEMPTS + 1):
        waited = 0.0
        if attempt > 1:
            # the shared retry budget spans ALL retries: a dead tunnel
            # costs BENCH_PROBE_RETRY_BUDGET total, never the wall
            if retry_deadline is None:
                retry_deadline = time.monotonic() + min(
                    PROBE_RETRY_BUDGET, _remaining() - 120
                )
            left = retry_deadline - time.monotonic()
            waited = _probe_backoff_s(attempt)
            if waited > left - 5:
                # the backoff would eat the attempt's own probe window:
                # stop BEFORE sleeping — burning wall on a wait whose
                # attempt can never run helps nobody
                break
            time.sleep(waited)
            left = retry_deadline - time.monotonic()
        else:
            left = max(5.0, deadline - time.monotonic())
        t0 = time.monotonic()
        if chaos.probe_timeout_pending():
            # the injected r02 death shape: this attempt hangs past its
            # timeout (no subprocess spawned — the verdict records the
            # same outcome the real hang would)
            err = "probe timed out (backend init hung; chaos-injected)"
            outcome = "probe-timeout"
            verdict["attempts"].append({
                "outcome": outcome, "wall_s": 0.0,
                "backoff_s": round(waited, 1), "detail": err,
            })
            print(f"# device probe failed (attempt {attempt}): {err}",
                  file=sys.stderr)
            continue
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp;"
                 "assert jax.devices()[0].platform == 'tpu';"
                 "print(int((jnp.ones((8,8))+1).sum()))"],
                capture_output=True, text=True,
                timeout=max(5.0, min(90.0, left)),
            )
            if probe.returncode == 0 and probe.stdout.strip() == "128":
                print(f"# device probe ok (attempt {attempt})",
                      file=sys.stderr)
                verdict["ok"] = True
                verdict["outcome"] = "ok"
                verdict["attempts"].append({
                    "outcome": "ok",
                    "wall_s": round(time.monotonic() - t0, 1),
                    "backoff_s": round(waited, 1),
                })
                return True, verdict
            err = (probe.stderr or "?").strip().splitlines()
            err = err[-1] if err else "?"
            outcome = "probe-error"
        except subprocess.TimeoutExpired:
            err = "probe timed out (backend init hung)"
            outcome = "probe-timeout"
        verdict["attempts"].append({
            "outcome": outcome, "wall_s": round(time.monotonic() - t0, 1),
            "backoff_s": round(waited, 1), "detail": str(err)[:200],
        })
        print(f"# device probe failed (attempt {attempt}): {err}",
              file=sys.stderr)
    # the banked classification: every attempt timed out vs at least one
    # answered wrongly (a reachable-but-broken backend is a different
    # bug than a wedged tunnel)
    outcomes = {a["outcome"] for a in verdict["attempts"]}
    verdict["outcome"] = ("backend-probe-timeout"
                          if outcomes == {"probe-timeout"}
                          else "backend-probe-error")
    return False, verdict


_DEVICE_CHILD = r"""
import faulthandler, hashlib, json, os, shutil, signal, sys, time

# a driver-timeout SIGTERM must leave a stack trace in the banked tail
# instead of an empty truncation: register BEFORE anything slow (jax
# import included) so even an import-time kill names where it was.
# stderr is teed into the parent's child log -> the round JSON tail.
faulthandler.register(signal.SIGTERM, all_threads=True, chain=True)

import jax

# --- persistent-cache keying + startup probe (VERDICT r6 item 1) -----------
# Four bench rounds died on "cached executable is axon format vN, this
# build is v9": every stale entry burned ~15 s failing to deserialize
# BEFORE the recompile even started. Two defenses:
#   1. the cache dir is KEYED by the runtime build-id (a slug of the
#      PJRT platform_version) under .bench_cache/ — a same-build rerun
#      starts warm, a new build starts empty instead of poisoned;
#   2. one entry of that dir is PROBE-DESERIALIZED at startup: if the
#      runtime rejects its own keyed cache (same marker, incompatible
#      binaries — the r2-r5 failure shape), the whole dir is wiped and
#      the AOT executable load path disabled for the run, so the ~15 s
#      rejection is paid ONCE, not once per stage per attempt.
try:
    build_id = jax.devices()[0].client.platform_version
except Exception:
    build_id = f"jax-{jax.__version__}"
slug = hashlib.blake2s(build_id.encode(), digest_size=6).hexdigest()
cache_dir = os.path.join(os.environ["OCT_JAX_CACHE_ROOT"], f"jax-{slug}")
os.makedirs(cache_dir, exist_ok=True)
# record the resolved dir so the parent's between-attempt wipe targets it
with open(os.environ["OCT_JAX_CACHE_PATH_FILE"], "w") as f:
    f.write(cache_dir)

# substrings that POSITIVELY identify a runtime-rejected executable
# format (the r2-r5 failure shape). Deliberately narrow: generic words
# like "deserialize" also appear in Python API-mismatch errors
# (TypeError naming the method), which must stay inconclusive.
_STALE_PATTERNS = ("axon format", "serialized executable is incompatible")


def _probe_cache_entry():
    entries = sorted(
        e for e in os.listdir(cache_dir)
        if os.path.isfile(os.path.join(cache_dir, e))
    )
    if not entries:
        return None, "empty"  # nothing to probe, nothing to lose
    path = os.path.join(cache_dir, entries[0])
    try:
        with open(path, "rb") as fh:
            blob = fh.read()
        try:  # jax compresses cache entries when zstandard is available
            import zstandard

            blob = zstandard.ZstdDecompressor().decompress(
                blob, max_output_size=1 << 31
            )
        except Exception:
            pass
        jax.devices()[0].client.deserialize_executable(blob)
        return True, "ok"
    except (TypeError, AttributeError):
        return None, "api-mismatch"  # probe API mismatch: inconclusive
    except Exception as e:  # noqa: BLE001 — classification only
        msg = str(e).lower()
        if any(p in msg for p in _STALE_PATTERNS):
            return False, str(e)  # positively identified stale entry
        return None, str(e)  # inconclusive (wrapper format, bad entry)


sys.path.insert(0, os.environ["OCT_REPO"])
from ouroboros_consensus_tpu import obs as _obs
from ouroboros_consensus_tpu.obs.resources import RESOURCES as _RESOURCES
from ouroboros_consensus_tpu.obs.warmup import WARMUP as _WARMUP

_t_probe = time.monotonic()
_probe_ok, _probe_detail = _probe_cache_entry()
_WARMUP.note_cache_probe(
    {True: "ok", False: "stale", None: "inconclusive"}[_probe_ok],
    time.monotonic() - _t_probe, _probe_detail,
)
if _probe_ok is False:
    print(f"# startup probe: {cache_dir} entries rejected by this "
          "runtime; wiping cache and skipping AOT load path",
          file=sys.stderr)
    shutil.rmtree(cache_dir, ignore_errors=True)
    os.makedirs(cache_dir, exist_ok=True)
    os.environ["OCT_PK_AOT"] = "0"

# The AOT artifact store (ops/pk/aot.py) is build-pinned: one query
# replaces the old BUILD_ID-marker heuristics — entries from another
# build are zero-cost wrong_build skips at load time, never doomed
# deserializes, so nothing needs disabling. Write-back is enabled so
# every stage THIS child compiles is re-serialized for this build:
# attempt 2 (and the next round) loads warm instead of recompiling.
from ouroboros_consensus_tpu.ops.pk import aot as _pk_aot

os.environ.setdefault("OCT_PK_AOT_WRITEBACK", "1")
_st = _pk_aot.store_status()
print(f"# aot store: {_st['matching']}/{_st['entries']} artifact(s) "
      f"match this build ({_st['stale_src']} stale-src)", file=sys.stderr)
_WARMUP.note(
    f"aot store: {_st['matching']}/{_st['entries']} artifacts match build"
)
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from bench import BENCH_HEADERS, KES_DEPTH, MAX_BATCH, bench_params, build_or_load_chain
from ouroboros_consensus_tpu.storage import sidecar as _sidecar
from ouroboros_consensus_tpu.tools import db_analyser as ana

# the flight recorder rides every replay (per-window spans, gate
# attribution, dispatch->materialize latency histograms) — per-window
# cost only, and the warmup recorder is flushing to OCT_WARMUP_REPORT
_rec = _obs.install()
# the LIVE plane for the child's whole life (not just inside each
# revalidate): heartbeat file every ~2 s + stall watchdog + optional
# in-run HTTP endpoint — the parent tails the heartbeat to classify
# this child in real time (obs/live.py; armed iff the levers are set,
# which the parent guarantees)
from ouroboros_consensus_tpu.obs import live as _live

_live.maybe_arm(_rec)

path, params, lview = build_or_load_chain()
def emit(n, best, warm, attrib=None, warm_estimate=None, resumed=0):
    # write-then-rename so a kill mid-write can't leave torn JSON.
    # warm_estimate_s: the parent's attempt-2 budget gate — how much wall
    # a fresh child needs before it can bank anything (measured, not
    # guessed; a prefix bank reports its own elapsed as a lower bound).
    # resumed_headers: headers a checkpoint resume skipped — the parent
    # rates the banked replay over its FRESH headers only, so a resumed
    # attempt can never inflate the device number.
    tmp = os.environ["OCT_RESULT"] + ".tmp"
    row = {"n": n, "best_s": best, "warm_s": warm,
           "warm_estimate_s": warm_estimate if warm_estimate else warm,
           "resumed_headers": int(resumed),
           "platform": jax.devices()[0].platform,
           "build_id": build_id,
           "warmup_report": _WARMUP.report(),
           "metrics_summary": _rec.latency_summary(),
           "metrics": _rec.registry.snapshot(),
           # per-stage FLOP/byte/HBM accounting of every program this
           # child actually dispatched (obs/resources.py)
           "device_resources": _RESOURCES.report()}
    if attrib:
        row.update(attrib)
    with open(tmp, "w") as f:
        json.dump(row, f)
    os.replace(tmp, os.environ["OCT_RESULT"])

def attribution(r):
    # per-phase wall + device-boundary bytes (collect_phases tracer):
    # transfer-tax regressions show in the bench trajectory, not only
    # in ad-hoc profiling
    if not r.n_windows:
        return None
    out = {
        "phases_s": {k: round(v, 2) for k, v in sorted(r.phases.items())},
        "windows": r.n_windows,
        "packed_windows": r.packed_windows,
        "h2d_bytes_per_window": int(r.h2d_bytes / r.n_windows),
        "d2h_bytes_per_window": int(r.d2h_bytes / r.n_windows),
    }
    # the store crash protocol (storage/guard.py): a replay that found
    # the store dirty (killed previous writer) deep-validated and
    # repaired it — bank the fact so perf_report can classify the
    # round repaired@<action> (detailed rows ride the warmup report)
    if r.opened_dirty:
        out["opened_dirty"] = True
    if r.repairs:
        out["repairs"] = dict(r.repairs)
    # columnar-sidecar outcomes of THIS replay (reset before each timed
    # run): hit/miss attribution for the view-stream wall — the
    # stream-mmap/stream-parse phases_s rows split the same wall
    sc = _sidecar.counters()
    if any(sc.values()):
        out["sidecar"] = sc
    return out

# Warm up compiles/cache-loads on the SMALL cached chain when the
# target is the 1M north star: a full-scale warmup replay would eat the
# wall budget that should go to measured hot replays. Batch shapes are
# bucketed, so the small chain exercises (nearly) all executables; any
# residual new shape compiles once inside the first timed replay and
# the second replay is clean.
warm_path = path
if BENCH_HEADERS > 200_000:
    small = os.path.join(os.path.dirname(path), f"chain_h100000_d{KES_DEPTH}")
    if os.path.exists(os.path.join(small, "COMPLETE")):
        warm_path = small
# the checkpoint plane (obs/recovery.py) belongs to the FULL-chain
# timed replays only: the prefix/warmup replays — usually on the small
# warm chain — must neither clobber the record a killed attempt left
# for the 1M chain nor mark it complete, so the levers are fenced off
# until the timed loop
_ckpt_lever = os.environ.pop("OCT_CHECKPOINT", None)
_resume_lever = os.environ.pop("OCT_RESUME", None)
_WARMUP.note("two-window prefix replay starting")
t0 = time.monotonic()
# EARLIEST bank (round-8): a two-window prefix replay first. It pays the
# production-bucket compiles and banks a real (conservative, compile-
# inclusive) end-to-end number within the first minutes — the r02..r05
# children all died at the wall having banked NOTHING because the first
# checkpoint waited for a full warmup replay (~410 s at r05).
r = ana.revalidate(warm_path, params, lview, backend="device",
                   validate_all="stream", max_batch=MAX_BATCH,
                   max_headers=2 * MAX_BATCH)
prefix_s = time.monotonic() - t0
assert r.error is None, repr(r.error)
assert r.n_valid == r.n_blocks > 0
emit(r.n_valid, prefix_s, prefix_s, warm_estimate=prefix_s)
_WARMUP.note(f"prefix replay banked after {prefix_s:.0f}s; full warmup next")
r = ana.revalidate(warm_path, params, lview, backend="device",
                   validate_all="stream", max_batch=MAX_BATCH)
warm_s = time.monotonic() - t0
assert r.error is None, repr(r.error)
assert r.n_valid == r.n_blocks > 0
# provisional checkpoint the MOMENT the first warm replay finishes
# (VERDICT r5 next #1b). The warmup IS a complete end-to-end replay —
# of the small chain when warming for the 1M target — so its rate is a
# real, conservative device number (includes compile/cache-load time);
# every later full-chain replay overwrites it with a better one.
emit(r.n_valid, warm_s, warm_s)
if _ckpt_lever is not None:
    os.environ["OCT_CHECKPOINT"] = _ckpt_lever
if _resume_lever is not None:
    os.environ["OCT_RESUME"] = _resume_lever
best_rate = None
for _ in range(2):
    t0 = time.monotonic()
    _sidecar.reset_counters()
    r = ana.revalidate(path, params, lview, backend="device",
                       validate_all="stream", max_batch=MAX_BATCH,
                       collect_phases=True)
    wall = time.monotonic() - t0
    # only the FIRST timed replay may resume a killed attempt's record;
    # the second is always a clean full replay (its own record was
    # marked complete, but the lever must not linger either)
    os.environ.pop("OCT_RESUME", None)
    assert r.error is None and r.n_valid == r.n_blocks
    fresh = r.n_valid - r.resumed_headers
    rate = fresh / wall if wall > 0 else 0.0
    # compare replays by FRESH-header rate: a resumed replay's shorter
    # wall covers fewer headers, so wall-compares would be apples to
    # oranges (and banking it raw would inflate the device number)
    if fresh > 0 and (best_rate is None or rate > best_rate):
        best_rate = rate
        emit(r.n_valid, wall, warm_s, attribution(r),
             resumed=r.resumed_headers)
"""


# the same executable-format rejection patterns the in-process AOT latch
# keys on (ops/pk/aot.py note_failure — one rejection now disables the
# remaining aot.load attempts inside the child; this parent-side grep
# only decides whether to wipe the persistent cache between attempts)
from ouroboros_consensus_tpu.ops.pk.aot import (  # noqa: E402
    INCOMPATIBLE_PATTERNS as _STALE_CACHE_RE,
)


def _wipe_stale_cache(child_log: str) -> None:
    """Belt-and-braces for the child's startup probe: if the child's
    log still shows executable-format rejections (entries the probe
    could not classify), wipe the resolved per-build JAX cache dir so
    the retry compiles clean instead of burning ~15 s per stale entry.
    Rejections the pk-aot loader itself reported (lines prefixed
    '# pk-aot:') implicate only the build-pinned artifact store, which
    SELF-HEALS via write-back — nothing to wipe, nothing to disable
    (pre-round-10 this returned a flag that switched AOT off for the
    retry; that trap is gone on purpose, hence no return value)."""
    flagged = [
        ln for ln in child_log.lower().splitlines()
        if any(pat in ln for pat in _STALE_CACHE_RE)
    ]
    if not flagged:
        return
    if all(ln.lstrip().startswith("# pk-aot:") for ln in flagged):
        # the build-pinned store SELF-HEALS: the rejected entry is
        # condemned by its marker and the write-back re-serialized a
        # fresh executable for this build, so the retry keeps AOT on
        # and loads warm (pre-round-10 this disabled AOT wholesale)
        print("# stale-executable rejections all came from the pk-aot "
              "store (self-healing via write-back): jax cache and AOT "
              "both kept for the retry", file=sys.stderr)
        return
    import shutil

    target = JAX_CACHE_ROOT
    try:
        with open(JAX_CACHE_PATH_FILE) as f:
            target = f.read().strip() or JAX_CACHE_ROOT
    except OSError:
        pass
    print(f"# stale-executable rejection in child log: wiping {target} "
          "for the retry", file=sys.stderr)
    shutil.rmtree(target, ignore_errors=True)


# the production packed-agg window pipeline's cold-compile set: the
# aggregate monolith + the packed unpack/reduce stages (the programs a
# fresh child must compile before its two-window prefix replay can
# bank anything). Used when no measured warm_estimate_s exists yet —
# the first round on a fresh build id previously had no gate at all.
_COLD_WALL_GRAPHS = ("aggregate_core", "packed_unpack", "verdict_reduce")
# dispatch/staging overhead on top of the compiles (chain open, synth
# cache read, H2D) — deliberately conservative
_COLD_WALL_OVERHEAD_S = 60.0


def _predicted_cold_wall() -> float | None:
    """Model-predicted cold warmup estimate for a fresh device child:
    the octwall pinned predictions (analysis/costmodel.json — dict
    lookups, no tracing) summed over the production window programs.
    None when the cost model has no pins for them."""
    try:
        from ouroboros_consensus_tpu.analysis import costmodel
    except Exception:
        return None
    walls = [costmodel.predicted_wall(g) for g in _COLD_WALL_GRAPHS]
    if any(w is None for w in walls):
        # a partial sum would UNDERSTATE the gate (e.g. the aggregate
        # pin missing leaves ~4s of unpack/reduce standing in for a
        # ~750s wall) — no estimate is safer than a wrong-by-100x one
        return None
    return sum(walls) + _COLD_WALL_OVERHEAD_S


def _attempt2_estimate(est: float | None, budget_1: float) -> float:
    """Wall a second cold start needs before it can bank anything.
    Preference order: the MEASURED warm_estimate_s the first attempt
    banked; else the octwall model-predicted cold wall (first round on
    a fresh build id has nothing banked yet); else half the first
    attempt's budget (the pre-model heuristic)."""
    if est is not None and est > 0:
        return est
    pred = _predicted_cold_wall()
    if pred is not None:
        print(f"# no banked warm estimate: using model-predicted cold "
              f"wall {pred:.0f}s as the attempt-2 gate", file=sys.stderr)
        return pred
    return budget_1 * 0.5


class _HeartbeatTail:
    """Parent-side tail of the child's heartbeat file: poll every few
    seconds, classify (obs/live.classify: compiling / staging / running
    / stalled / dead / no-heartbeat), and record a STRUCTURED timeline
    entry at every classification change — the live story of the round,
    banked into the round JSON + ledger as `live_timeline` so a dead
    round's last entry says what it LOOKED like when it died."""

    POLL_S = 3.0

    def __init__(self, path: str, timeline: list, attempt: int):
        import threading

        from ouroboros_consensus_tpu.obs import live as _live

        self._live = _live
        self.path = path
        self.timeline = timeline
        self.attempt = attempt
        self._t0 = time.monotonic()
        self._state = None
        self.errors = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="bench-hb-tail", daemon=True
        )
        self._thread.start()

    def _poll(self) -> None:
        doc = self._live.read_heartbeat(self.path)
        state = self._live.classify(doc)
        if state == self._state:
            return
        self._state = state
        entry = {
            "t": round(time.monotonic() - self._t0, 1),
            "attempt": self.attempt,
            "state": state,
        }
        if isinstance(doc, dict):
            entry["phase"] = doc.get("phase")
            entry["headers"] = doc.get("headers")
            entry["age_s"] = doc.get("age_s")
            if doc.get("headers_per_s") is not None:
                entry["headers_per_s"] = doc["headers_per_s"]
        self.timeline.append(entry)
        print(f"# live: {state}"
              + (f" (phase={entry.get('phase')}, "
                 f"headers={entry.get('headers')})"
                 if "phase" in entry else ""),
              file=sys.stderr)

    def _run(self) -> None:
        while not self._stop.wait(self.POLL_S):
            try:
                self._poll()
            except Exception as exc:  # noqa: BLE001 — tailing never
                self._note_tail_error(exc)  # kills bench, nor hides

    def _note_tail_error(self, exc: BaseException) -> None:
        """Tail failures ride the timeline they were hiding from: one
        `tail-error` entry for the FIRST failure (bounded — a wedged
        reader would otherwise spam an entry per poll), plus a count
        any later entry's consumer can see on the object."""
        self.errors += 1
        if self.errors != 1:
            return
        self.timeline.append({
            "t": round(time.monotonic() - self._t0, 1),
            "attempt": self.attempt,
            "state": "tail-error",
            "error": f"{type(exc).__name__}: {exc}"[:200],
        })

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self.POLL_S + 5)
        try:
            self._poll()  # final classification (usually dead/finished)
        except Exception:  # noqa: BLE001
            pass


def _read_stall_dump(path: str | None = None) -> dict | None:
    """Read + slim the child's stall forensics (obs/live.StallWatchdog):
    keep the classification and the trimmed per-thread stack tails —
    enough to name the wedged stage in the round JSON without banking
    hundreds of full frames."""
    path = path or _stall_dump_path()
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    slim = {k: doc.get(k) for k in
            ("ts_unix", "phase", "age_s", "budget_s", "pid")}
    threads = doc.get("threads") or {}
    slim["threads"] = {
        name: frames[-6:] for name, frames in threads.items()
    }
    hb = doc.get("heartbeat")
    if isinstance(hb, dict):
        slim["heartbeat"] = {
            k: hb.get(k) for k in ("phase", "headers", "age_s", "seq")
        }
    return slim


def _run_teed(cmd, env, budget, log_path, watch=None):
    """Popen with stdout teed to stderr AND `log_path`, killed at
    `budget` seconds -> (proc, timed_out, policy_killed).

    `watch` (optional) is polled every few seconds while the child
    runs; when it returns "kill" the child is SIGTERM'd for forensics
    (its registered faulthandler banks all-thread stacks into the teed
    log), then killed — the bench parent's side of the recovery policy
    (obs/recovery.ParentPolicy): a child whose heartbeat says stalled/
    dead past its grace is relaunched with resume instead of burning
    the remaining wall."""
    import threading

    from ouroboros_consensus_tpu.obs import recovery as _recovery

    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )

    def pump():
        with open(log_path, "w") as log_f:
            for raw in proc.stdout:
                line = raw.decode("utf-8", "replace")
                sys.stderr.write(line)
                log_f.write(line)
                log_f.flush()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    timed_out = False
    policy_killed = False
    deadline = time.monotonic() + budget
    while True:
        try:
            proc.wait(timeout=3.0)
            break
        except subprocess.TimeoutExpired:
            if time.monotonic() >= deadline:
                timed_out = True
                proc.kill()
                proc.wait()
                break
            if watch is not None and watch() == "kill":
                policy_killed = True
                _recovery.terminate_for_forensics(proc)
                break
    t.join(timeout=10)
    return proc, timed_out, policy_killed


def run_device_subprocess() -> tuple[dict | None, list]:
    """Run the device-side replay in a child with a hard wall budget.
    Returns (banked result or None, the live-classification timeline
    the parent tailed off the child's heartbeat)."""
    result_path = os.path.join(CACHE, "device_result.json")
    try:
        os.remove(result_path)
    except FileNotFoundError:
        pass
    env = dict(os.environ)
    env["OCT_RESULT"] = result_path
    env["OCT_REPO"] = os.path.dirname(os.path.abspath(__file__))
    env["OCT_JAX_CACHE_ROOT"] = JAX_CACHE_ROOT
    env["OCT_JAX_CACHE_PATH_FILE"] = JAX_CACHE_PATH_FILE
    # crash-safe warmup forensics: flushed per note, read back even
    # when the child dies on the compile wall with nothing else banked
    env["OCT_WARMUP_REPORT"] = _warmup_report_path()
    # the live plane: the child beats a heartbeat file every ~2 s and
    # arms the stall watchdog; the parent tails the file into a
    # structured timeline (setdefault: the operator's own levers win)
    env.setdefault("OCT_HEARTBEAT", _heartbeat_path())
    env.setdefault("OCT_STALL_BUDGET_S", STALL_BUDGET_S)
    # crash-consistent checkpointing (obs/recovery.py): the child's
    # full-chain replays persist a progress record per retired window,
    # so a killed/stalled attempt RESUMES from the last retired window
    # instead of restarting from header zero (the r02-r05 shape)
    env.setdefault("OCT_CHECKPOINT", os.path.join(CACHE, "checkpoint.json"))
    timeline: list = []
    # Two attempts inside the budget: the pk dispatch is per-stage jits
    # (ops/pk/kernels.verify_praos_split), so every stage a killed child
    # DID compile is already in the persistent cache — the retry resumes
    # at the first uncompiled stage instead of starting over. First
    # attempt gets the lion's share; the retry only makes sense if real
    # time remains — MEASURED against the warmup the first attempt saw,
    # not hoped (r05 gave attempt 2 a 109 s budget against a ~410 s
    # warmup: pure waste that also risked clobbering the banked json).
    budget_1 = 0.0
    for attempt in (1, 2):
        budget = min(DEVICE_BUDGET, _remaining() - 30)  # 30s to emit
        if budget <= 60:
            print("# no wall budget left for the device measurement",
                  file=sys.stderr)
            break
        if attempt == 1:
            budget = min(budget, max(60.0, _remaining() * 0.85))
            budget_1 = budget
        else:
            est = None
            try:
                with open(result_path) as f:
                    est = float(json.load(f).get("warm_estimate_s") or 0)
            except (OSError, ValueError, json.JSONDecodeError):
                pass
            # no checkpoint after attempt 1 means even the two-window
            # prefix replay did not fit — gate on the model-predicted
            # cold wall (or the pre-model half-budget heuristic)
            est = _attempt2_estimate(est, budget_1)
            if budget < est + 60:
                # est may be MEASURED (banked warm_estimate_s) or the
                # octwall model PREDICTION — _attempt2_estimate said
                # which on stderr just above
                print(
                    f"# skipping device attempt 2: {budget:.0f}s left < "
                    f"warmup estimate {est:.0f}s + 60s margin "
                    "(keeping any banked checkpoint)",
                    file=sys.stderr,
                )
                break
        # the child's output is teed LIVE to stderr and to a log file,
        # so the operator still sees compile/replay progress while the
        # parent can grep the log for stale-executable rejections
        # between attempts
        child_log_path = os.path.join(CACHE, f"device_child_{attempt}.log")
        # octwall pre-flight: the child's dispatch gate refuses any COLD
        # monolith whose predicted compile wall does not fit what is
        # left of THIS attempt's budget (analysis/costmodel.preflight —
        # refusals recorded in the warmup report)
        env["OCT_WALL_DEADLINE"] = str(time.time() + budget)
        # stale beats must never be read as THIS attempt's story: the
        # parent's own native-baseline replay (armed when the watchdog
        # script exports OCT_HEARTBEAT) and attempt 1 both wrote to
        # this path — the tail classifies only what this child beats
        try:
            os.remove(env["OCT_HEARTBEAT"])
        except OSError:
            pass
        tail = _HeartbeatTail(env["OCT_HEARTBEAT"], timeline, attempt)
        # the parent's escalation policy (obs/recovery.ParentPolicy):
        # a child continuously stalled (its own watchdog tripped) or
        # dead (heartbeat stopped) past its grace is SIGTERM'd for
        # forensics and relaunched with resume — the retry pays only
        # the un-banked suffix of the replay
        from ouroboros_consensus_tpu.obs import live as _live
        from ouroboros_consensus_tpu.obs import recovery as _recovery

        policy = _recovery.ParentPolicy()

        def _watch(_hb=env["OCT_HEARTBEAT"], _policy=policy):
            doc = _live.read_heartbeat(_hb)
            return _policy.observe(_live.classify(doc))

        try:
            proc, timed_out, policy_killed = _run_teed(
                [sys.executable, "-c", _DEVICE_CHILD], env, budget,
                child_log_path, watch=_watch,
            )
        finally:
            tail.stop()
        try:
            with open(child_log_path) as f:
                child_log = f.read()
        except OSError:
            child_log = ""
        # a jax-cache wipe is all a stale-executable rejection costs now:
        # the pk-aot store is build-pinned + self-healing, so the retry
        # keeps the AOT load path (it will find the written-back entries)
        _wipe_stale_cache(child_log)
        if policy_killed:
            # relaunch-with-resume: the child's checkpoint holds the
            # last retired window; OCT_RESUME makes the retry's
            # full-chain replay skip the banked prefix
            print(
                f"# device attempt {attempt} killed by the stall policy "
                "(SIGTERM'd for forensics; relaunching with resume)",
                file=sys.stderr,
            )
            env["OCT_RESUME"] = "1"
            continue
        if timed_out:
            # a timeout after the warmup replay still yields a real
            # end-to-end number — read the provisional checkpoint; if
            # there is none, the retry rides the now-warmer cache (and
            # resumes the replay from the progress record)
            print(
                f"# device attempt {attempt} exceeded {budget:.0f}s "
                "budget (keeping any provisional checkpoint)",
                file=sys.stderr,
            )
            env["OCT_RESUME"] = "1"
            if not os.path.exists(result_path):
                continue
        elif proc.returncode != 0:
            # an assertion/crash in the child means the device
            # produced WRONG results — never report its checkpoint
            print(f"# device measurement failed rc={proc.returncode}",
                  file=sys.stderr)
            return None, timeline
        break
    try:
        with open(result_path) as f:
            return json.load(f), timeline
    except (OSError, json.JSONDecodeError):
        return None, timeline


def append_ledger_record(out: dict, baseline: float | None = None,
                         native_wall_s: float | None = None,
                         probe: dict | None = None) -> dict | None:
    """One provenance-complete run-ledger record per bench run
    (obs/ledger.py): the final JSON line plus git rev/dirty, the child's
    PJRT build id, every OCT_*/BENCH_* kill-switch value, the warmup
    forensics, metrics snapshot and per-stage device resources — so
    "what changed between r01 and r02" is a ledger query, not
    BENCH_r0*.json archaeology. Fail-soft: the bench's one JSON line
    must come out even if the ledger cannot (read-only disk, etc.)."""
    try:
        from ouroboros_consensus_tpu.obs import ledger

        big = ("metrics", "metrics_summary", "warmup_report",
               "device_resources", "live_timeline", "stall_dump")
        slim = {k: v for k, v in out.items() if k not in big}
        extra = {}
        if out.get("live_timeline"):
            extra["live_timeline"] = out["live_timeline"]
        if out.get("stall_dump"):
            extra["stall_dump"] = out["stall_dump"]
        if baseline is not None:
            extra["native_baseline_per_s"] = round(baseline, 1)
            if native_wall_s is not None:
                extra["native_wall_s"] = round(native_wall_s, 1)
        if probe is not None:
            # the probe verdict rides the ledger so a dead round's
            # attribution (probe-timeout vs driver-timeout) is a query
            extra["probe"] = probe
        extra = extra or None
        return ledger.record_run(
            "bench",
            config={
                "headers": BENCH_HEADERS, "max_batch": MAX_BATCH,
                "kes_depth": KES_DEPTH,
                "total_budget_s": TOTAL_BUDGET,
                "device_budget_s": DEVICE_BUDGET,
            },
            result=slim,
            wall_s=time.monotonic() - _T0,
            phases_s=out.get("phases_s"),
            warmup_report=out.get("warmup_report"),
            metrics=out.get("metrics"),
            metrics_summary=out.get("metrics_summary"),
            device_resources=out.get("device_resources"),
            build_id=out.get("build_id"),
            extra=extra,
        )
    except Exception:  # noqa: BLE001 — the ledger never breaks the bench
        return None


def main() -> None:
    # forensics left by a PREVIOUS round must never be banked as this
    # round's — only the child this run spawns may write them
    for stale in (_warmup_report_path(), _heartbeat_path(),
                  _stall_dump_path()):
        try:
            os.remove(stale)
        except OSError:
            pass
    # The native baseline and chain synthesis need no accelerator; run
    # them FIRST so a wedged tunnel can never cost us the whole round.
    path, params, lview = build_or_load_chain()

    from ouroboros_consensus_tpu.tools import db_analyser as ana

    # the native RATE is constant per header; at the 1M scale, measure
    # it on a 200k prefix of the SAME chain so the wall ceiling converts
    # into device measurement instead of a second 7-minute native replay.
    # validate_all="stream" folds the ValidateAllChunks walk into the
    # replay's own reads (one disk pass, same checks) for BOTH backends;
    # the prefix rate excludes the open wall (index loads for the FULL
    # chain) so the 1M-chain open cannot deflate a 200k-prefix baseline
    # — conservative for vs_baseline, since the device rate keeps its
    # own open in its wall.
    native_cap = 200_000 if BENCH_HEADERS > 200_000 else None
    t0 = time.monotonic()
    r = ana.revalidate(path, params, lview, backend="native",
                       validate_all="stream", max_batch=MAX_BATCH,
                       max_headers=native_cap)
    nwall = time.monotonic() - t0
    assert r.error is None, f"bench chain must revalidate clean: {r.error!r}"
    assert r.n_valid == r.n_blocks > 0
    baseline = r.n_valid / (nwall - (r.open_s if native_cap else 0.0))
    cap_note = (
        f" (rate over a {r.n_valid}-header prefix, open {r.open_s:.1f}s "
        "excluded)" if native_cap else ""
    )
    print(f"# native baseline {baseline:.0f} headers/s ({nwall:.1f}s){cap_note}",
          file=sys.stderr)

    probe_ok, probe_verdict = probe_device()
    live_timeline: list = []
    if probe_ok:
        device, live_timeline = run_device_subprocess()
        # the probe SUCCEEDED, so a missing device result is a run/wall
        # death — classified distinctly from a probe death in the
        # banked tail (perf_report tells them apart structurally now)
        why_no_device = "device run failed or ran out of wall budget"
        no_device_reason = "device-run-failed-or-wall"
    else:
        device = None
        why_no_device = (
            f"backend probe failed ({probe_verdict.get('outcome')})"
        )
        no_device_reason = probe_verdict.get("outcome", "backend-probe")

    if device is not None:
        # rate over the FRESH headers of the banked replay: a resumed
        # attempt validated only the un-banked suffix in best_s, so the
        # resumed prefix must not inflate the number
        resumed = int(device.get("resumed_headers") or 0)
        rate = (device["n"] - resumed) / device["best_s"]
        print(
            f"# platform={device['platform']} headers={device['n']} "
            f"warmup={device['warm_s']:.1f}s best={device['best_s']:.2f}s"
            + (f" (resumed past {resumed} banked headers)" if resumed
               else ""),
            file=sys.stderr,
        )
        out = {
            "metric": (
                "end-to-end db-analyser revalidation of a "
                f"{device['n']}-header synthetic Praos chain (disk->parse->"
                "stage->Pallas Ed25519+KES+VRF+leader kernels->nonce fold), "
                "TPU vs measured single-core C++ (libsodium-class) replay"
                + (f"; native rate measured over a {r.n_valid}-header "
                   "prefix of the same chain" if native_cap else "")
            ),
            "value": round(rate, 1),
            "unit": "headers/s",
            "vs_baseline": round(rate / baseline, 2),
        }
        # per-phase wall + boundary-byte attribution from the child's
        # best replay (ana.revalidate collect_phases tracer), plus the
        # warmup forensics and the flight recorder's metrics snapshot
        for k in ("phases_s", "windows", "packed_windows",
                  "h2d_bytes_per_window", "d2h_bytes_per_window",
                  "warmup_report", "metrics_summary", "metrics",
                  "device_resources", "build_id", "resumed_headers"):
            if k in device:
                out[k] = device[k]
        if "warmup_report" not in out:
            wr = _read_warmup_report()
            if wr is not None:
                out["warmup_report"] = wr
        out["probe"] = probe_verdict
        # a round that banked THROUGH the warm ladder is its own class
        # of round (perf_report renders it), not a warmup death
        ladder_evs = (out.get("warmup_report") or {}).get("ladder") or []
        if ladder_evs:
            out["laddered"] = True
    else:
        out = {
            "metric": (
                "end-to-end db-analyser revalidation of a "
                f"{BENCH_HEADERS}-header synthetic Praos chain — NO "
                f"DEVICE RESULT this run ({why_no_device}); value is "
                "the measured single-core C++ native-backend replay"
                + (f" (rate over a {r.n_valid}-header prefix, open wall "
                   "excluded)" if native_cap else "")
            ),
            "value": round(baseline, 1),
            "unit": "headers/s",
            "vs_baseline": 1.0,
            "device_unavailable": True,
        }
        out["no_device_reason"] = no_device_reason
        out["probe"] = probe_verdict
        # the whole point of the flight recorder: a warmup death still
        # banks a per-stage diagnosis (which compile/cache path ate the
        # wall), not just a timeout
        wr = _read_warmup_report()
        if wr is not None:
            out["warmup_report"] = wr
    # the live story of the round: the parent-tailed heartbeat timeline
    # plus any stall forensics the child's watchdog dumped — banked for
    # banked AND dead rounds (a dead round's last timeline entry is its
    # cause-of-death evidence; perf_report classifies stalled@<phase>)
    if live_timeline:
        out["live_timeline"] = live_timeline
    stall_dump = _read_stall_dump()
    if stall_dump is not None:
        out["stall_dump"] = stall_dump
    print(json.dumps(out))
    append_ledger_record(out, baseline=baseline, native_wall_s=nwall,
                         probe=probe_verdict)


if __name__ == "__main__":
    main()
