"""North-star benchmark: Praos headers fully validated per second.

Measures the fused batched hot path (protocol/batch.py: Ed25519 OCert
verify + CompactSum KES verify + ECVRF verify + leader threshold + nonce
range extension — the per-header crypto of Praos.hs:441-606) on the
available accelerator, and compares against a libsodium-class single-core
CPU baseline measured live with the `cryptography` package (OpenSSL
Ed25519).

Baseline model (BASELINE.md config 1): one header costs ≈ 2 Ed25519
verifies (OCert DSIGN + KES leaf) + 1 ECVRF verify (≈ 4 Ed25519-equivalent
scalar mults: 2 fixed-base + 2 variable-base in ietfdraft03 verify) +
~8 Blake2b hashes (negligible) ⇒ 6 Ed25519-equivalents/header. The CPU
baseline is therefore measured_openssl_ed25519_rate / 6 — matching what a
sequential libsodium fold (the reference's db-analyser --only-validation
loop) achieves per core.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "headers/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

BENCH_BATCH = int(os.environ.get("BENCH_BATCH", "4096"))
BENCH_ITERS = int(os.environ.get("BENCH_ITERS", "5"))
KES_DEPTH = int(os.environ.get("BENCH_KES_DEPTH", "7"))
CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_cache")


def build_or_load_batch():
    """Forge BENCH_BATCH protocol-valid headers (cached across runs —
    host-side signing is ~35ms/header) and stage them columnar."""
    import numpy as np

    from ouroboros_consensus_tpu.protocol import batch as pbatch
    from ouroboros_consensus_tpu.protocol import praos
    from ouroboros_consensus_tpu.testing import fixtures

    from fractions import Fraction

    params = praos.PraosParams(
        slots_per_kes_period=3600,
        max_kes_evolutions=62,
        security_param=2160,
        active_slot_coeff=Fraction(1, 20),  # mainnet f
        epoch_length=432_000,
        kes_depth=KES_DEPTH,
    )
    npz = os.path.join(CACHE, f"praos_batch_b{BENCH_BATCH}_d{KES_DEPTH}.npz")
    names = [
        "ed_pk", "ed_r", "ed_s", "ed_hblocks", "ed_hnblocks",
        "kes_vk", "kes_period", "kes_r", "kes_s", "kes_vk_leaf",
        "kes_siblings", "kes_hblocks", "kes_hnblocks",
        "vrf_pk", "vrf_gamma", "vrf_c", "vrf_s", "vrf_alpha",
        "beta", "thr_lo", "thr_hi",
    ]
    if os.path.exists(npz):
        z = np.load(npz)
        cols = [z[n] for n in names]
        from ouroboros_consensus_tpu.ops.ed25519_batch import Ed25519Batch
        from ouroboros_consensus_tpu.ops.ecvrf_batch import EcvrfBatch
        from ouroboros_consensus_tpu.ops.kes_batch import KesBatch

        return pbatch.PraosBatch(
            Ed25519Batch(*cols[0:5]), KesBatch(*cols[5:13]),
            EcvrfBatch(*cols[13:18]), cols[18], cols[19], cols[20],
        ), params

    # forge a fresh epoch-uniform batch: distinct slots, one pool
    # (validation cost is identical across issuers — crypto dominates)
    pool = fixtures.make_pool(0, kes_depth=KES_DEPTH)
    lview = fixtures.make_ledger_view([pool], stakes=None)
    nonce = b"\x07" * 32
    hvs = []
    t0 = time.monotonic()
    prev = None
    for i in range(BENCH_BATCH):
        hv = fixtures.forge_header_view(
            params, pool, slot=i + 1, epoch_nonce=nonce,
            prev_hash=prev, body_bytes=b"body-%d" % i,
        )
        hvs.append(hv)
        prev = b"%032d" % i
        if i and i % 512 == 0:
            print(
                f"# forged {i}/{BENCH_BATCH} ({(time.monotonic()-t0):.0f}s)",
                file=sys.stderr,
            )
    pre = pbatch.host_prechecks(params, lview, hvs)
    batch = pbatch.stage(params, lview, nonce, hvs, pre.kes_evolution)
    os.makedirs(CACHE, exist_ok=True)
    flat = pbatch.flatten_batch(batch)
    np.savez_compressed(npz, **{n: np.asarray(c) for n, c in zip(names, flat)})
    return batch, params


def measure_cpu_baseline() -> float:
    """Single-core libsodium-class headers/s (see module docstring)."""
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey,
        )
    except Exception:
        return 4200.0 / 6.0  # recorded OpenSSL rate on this image's CPU
    sk = Ed25519PrivateKey.generate()
    pk = sk.public_key()
    msg = b"x" * 256
    sig = sk.sign(msg)
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 1.0:
        for _ in range(200):
            pk.verify(sig, msg)
        n += 200
    rate = n / (time.perf_counter() - t0)
    return rate / 6.0


def main() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/ouroboros-jax-cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import numpy as np

    from ouroboros_consensus_tpu.protocol import batch as pbatch

    batch, params = build_or_load_batch()
    b = batch.beta.shape[0]
    platform = jax.devices()[0].platform

    # warmup: compile + first run
    t0 = time.monotonic()
    v = pbatch.run_batch(batch)
    warm_s = time.monotonic() - t0
    n_ok = int(np.sum(v.ok_ocert_sig & v.ok_kes_sig & v.ok_vrf))
    assert n_ok == b, f"benchmark batch must verify clean: {n_ok}/{b}"

    times = []
    for _ in range(BENCH_ITERS):
        t0 = time.perf_counter()
        pbatch.run_batch(batch)
        times.append(time.perf_counter() - t0)
    best = min(times)
    rate = b / best

    baseline = measure_cpu_baseline()
    print(
        f"# platform={platform} batch={b} warmup={warm_s:.1f}s "
        f"best={best*1e3:.1f}ms cpu_baseline={baseline:.0f}/s",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "praos headers fully validated (Ed25519+KES+VRF+leader) per second",
                "value": round(rate, 1),
                "unit": "headers/s",
                "vs_baseline": round(rate / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
