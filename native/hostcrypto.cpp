// hostcrypto.cpp — single-core C++ verification path for the Praos header
// crypto: Ed25519 (cofactorless, RFC 8032), ECVRF-ed25519-sha512-ell2
// (draft-03) and CompactSum KES, plus SHA-512 and Blake2b-256.
//
// Purpose: (1) the HONEST measured CPU baseline for bench.py — the same
// role libsodium plays under the reference's db-analyser revalidation
// fold (ouroboros-consensus-protocol/.../Protocol/Praos.hs:543,580,582
// via cardano-crypto-{class,praos}); (2) a fast host fallback for
// db_analyser --backend native. Written from the curve/protocol specs to
// mirror ops/host/{ed25519,ecvrf,kes}.py bit-for-bit (differentially
// tested in tests/test_native_crypto.py).
//
// Build: g++ -O2 -shared -fPIC -o libhostcrypto.so hostcrypto.cpp

#include <cstdint>
#include <cstring>
#include <cstddef>

typedef unsigned __int128 u128;
typedef uint64_t u64;
typedef uint8_t u8;

// ===========================================================================
// SHA-512
// ===========================================================================

static const u64 SHA_K[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

static inline u64 rotr64(u64 x, int n) { return (x >> n) | (x << (64 - n)); }

struct Sha512 {
    u64 h[8];
    u8 buf[128];
    u64 total;
    size_t fill;

    void init() {
        static const u64 H0[8] = {
            0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
            0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
            0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
        memcpy(h, H0, sizeof h);
        total = 0;
        fill = 0;
    }
    void block(const u8* p) {
        u64 w[80];
        for (int i = 0; i < 16; i++) {
            w[i] = 0;
            for (int j = 0; j < 8; j++) w[i] = (w[i] << 8) | p[8 * i + j];
        }
        for (int i = 16; i < 80; i++) {
            u64 s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
            u64 s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        u64 a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5], g = h[6],
            hh = h[7];
        for (int i = 0; i < 80; i++) {
            u64 S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
            u64 ch = (e & f) ^ (~e & g);
            u64 t1 = hh + S1 + ch + SHA_K[i] + w[i];
            u64 S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
            u64 maj = (a & b) ^ (a & c) ^ (b & c);
            u64 t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }
    void update(const u8* p, size_t n) {
        total += n;
        while (n) {
            size_t k = 128 - fill;
            if (k > n) k = n;
            memcpy(buf + fill, p, k);
            fill += k; p += k; n -= k;
            if (fill == 128) { block(buf); fill = 0; }
        }
    }
    void final(u8 out[64]) {
        u64 bits = total * 8;
        u8 pad = 0x80;
        update(&pad, 1);
        u8 z = 0;
        while (fill != 112) update(&z, 1);
        u8 len[16] = {0};
        for (int i = 0; i < 8; i++) len[15 - i] = (u8)(bits >> (8 * i));
        update(len, 16);
        for (int i = 0; i < 8; i++)
            for (int j = 0; j < 8; j++) out[8 * i + j] = (u8)(h[i] >> (56 - 8 * j));
    }
};

static void sha512(const u8* p, size_t n, u8 out[64]) {
    Sha512 s; s.init(); s.update(p, n); s.final(out);
}

// ===========================================================================
// Blake2b (RFC 7693), digest sizes 1..64
// ===========================================================================

static const u8 B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

static const u64 B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static void b2b_compress(u64 h[8], const u8 blk[128], u64 t, int last) {
    u64 v[16], m[16];
    for (int i = 0; i < 8; i++) { v[i] = h[i]; v[i + 8] = B2B_IV[i]; }
    v[12] ^= t;
    if (last) v[14] = ~v[14];
    for (int i = 0; i < 16; i++) {
        m[i] = 0;
        for (int j = 7; j >= 0; j--) m[i] = (m[i] << 8) | blk[8 * i + j];
    }
#define G(a, b, c, d, x, y)                                  \
    v[a] = v[a] + v[b] + (x); v[d] = rotr64(v[d] ^ v[a], 32); \
    v[c] = v[c] + v[d];       v[b] = rotr64(v[b] ^ v[c], 24); \
    v[a] = v[a] + v[b] + (y); v[d] = rotr64(v[d] ^ v[a], 16); \
    v[c] = v[c] + v[d];       v[b] = rotr64(v[b] ^ v[c], 63)
    for (int r = 0; r < 12; r++) {
        const u8* s = B2B_SIGMA[r];
        G(0, 4, 8, 12, m[s[0]], m[s[1]]);
        G(1, 5, 9, 13, m[s[2]], m[s[3]]);
        G(2, 6, 10, 14, m[s[4]], m[s[5]]);
        G(3, 7, 11, 15, m[s[6]], m[s[7]]);
        G(0, 5, 10, 15, m[s[8]], m[s[9]]);
        G(1, 6, 11, 12, m[s[10]], m[s[11]]);
        G(2, 7, 8, 13, m[s[12]], m[s[13]]);
        G(3, 4, 9, 14, m[s[14]], m[s[15]]);
    }
#undef G
    for (int i = 0; i < 8; i++) h[i] ^= v[i] ^ v[i + 8];
}

static void blake2b(const u8* p, size_t n, u8* out, int outlen) {
    u64 h[8];
    memcpy(h, B2B_IV, sizeof h);
    h[0] ^= 0x01010000ULL ^ (u64)outlen;  // no key
    u64 t = 0;
    u8 blk[128];
    while (n > 128) {
        memcpy(blk, p, 128);
        t += 128;
        b2b_compress(h, blk, t, 0);
        p += 128; n -= 128;
    }
    memset(blk, 0, 128);
    memcpy(blk, p, n);
    t += n;
    b2b_compress(h, blk, t, 1);
    for (int i = 0; i < outlen; i++) out[i] = (u8)(h[i / 8] >> (8 * (i % 8)));
}

// ===========================================================================
// GF(2^255-19), radix-51
// ===========================================================================

struct fe { u64 v[5]; };
static const u64 M51 = (1ULL << 51) - 1;

static inline u64 load64(const u8* p) {
    u64 r = 0;
    for (int i = 7; i >= 0; i--) r = (r << 8) | p[i];
    return r;
}

static void fe_frombytes(fe* o, const u8 b[32]) {
    // value mod 2^255 (top bit ignored by callers that mask it)
    o->v[0] = load64(b) & M51;
    o->v[1] = (load64(b + 6) >> 3) & M51;
    o->v[2] = (load64(b + 12) >> 6) & M51;
    o->v[3] = (load64(b + 19) >> 1) & M51;
    o->v[4] = (load64(b + 24) >> 12) & M51;
}

static void fe_carry(fe* f) {
    for (int pass = 0; pass < 2; pass++) {
        u64 c = 0;
        for (int i = 0; i < 5; i++) {
            u64 t = f->v[i] + c;
            f->v[i] = t & M51;
            c = t >> 51;
        }
        f->v[0] += 19 * c;
    }
}

static void fe_tobytes(u8 b[32], const fe* f0) {
    // canonical encoding: add 19 to detect g >= p, fold the would-be
    // carry back as +19, then drop bit 255
    fe g = *f0;
    fe_carry(&g);
    u64 q = (g.v[0] + 19) >> 51;
    q = (g.v[1] + q) >> 51;
    q = (g.v[2] + q) >> 51;
    q = (g.v[3] + q) >> 51;
    q = (g.v[4] + q) >> 51;  // q = 1 iff g >= p
    g.v[0] += 19 * q;
    u64 c = 0;
    for (int i = 0; i < 5; i++) {
        u64 t = g.v[i] + c;
        g.v[i] = t & M51;
        c = t >> 51;
    }
    g.v[4] &= M51;  // drop bit 255 (the wrapped 2^255 when g >= p)
    u64 w[4];
    w[0] = g.v[0] | (g.v[1] << 51);
    w[1] = (g.v[1] >> 13) | (g.v[2] << 38);
    w[2] = (g.v[2] >> 26) | (g.v[3] << 25);
    w[3] = (g.v[3] >> 39) | (g.v[4] << 12);
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++) b[8 * i + j] = (u8)(w[i] >> (8 * j));
}

// every op keeps limbs nearly normalized (< 2^51 + eps): add/sub run one
// light carry pass so their outputs are safe as subtrahends of the next
// fe_sub (whose 8p bias caps the subtrahend at ~2^54)
static inline void fe_lightcarry(fe* o) {
    u64 c = 0;
    for (int i = 0; i < 5; i++) {
        u64 t = o->v[i] + c;
        o->v[i] = t & M51;
        c = t >> 51;
    }
    o->v[0] += 19 * c;
}

static inline void fe_add(fe* o, const fe* a, const fe* b) {
    for (int i = 0; i < 5; i++) o->v[i] = a->v[i] + b->v[i];
    fe_lightcarry(o);
}

static inline void fe_sub(fe* o, const fe* a, const fe* b) {
    // a + 8p - b, limb-wise non-negative for operand limbs < 2^54
    o->v[0] = a->v[0] + 0x3FFFFFFFFFFF68ULL - b->v[0];
    for (int i = 1; i < 5; i++)
        o->v[i] = a->v[i] + 0x3FFFFFFFFFFFF8ULL - b->v[i];
    fe_lightcarry(o);
}

static void fe_mul(fe* o, const fe* a, const fe* b) {
    u64 a0 = a->v[0], a1 = a->v[1], a2 = a->v[2], a3 = a->v[3], a4 = a->v[4];
    u64 b0 = b->v[0], b1 = b->v[1], b2 = b->v[2], b3 = b->v[3], b4 = b->v[4];
    u64 b1_19 = b1 * 19, b2_19 = b2 * 19, b3_19 = b3 * 19, b4_19 = b4 * 19;
    u128 t0 = (u128)a0 * b0 + (u128)a1 * b4_19 + (u128)a2 * b3_19 +
              (u128)a3 * b2_19 + (u128)a4 * b1_19;
    u128 t1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * b4_19 +
              (u128)a3 * b3_19 + (u128)a4 * b2_19;
    u128 t2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0 +
              (u128)a3 * b4_19 + (u128)a4 * b3_19;
    u128 t3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1 +
              (u128)a3 * b0 + (u128)a4 * b4_19;
    u128 t4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2 +
              (u128)a3 * b1 + (u128)a4 * b0;
    // 128-bit carries: with lazy (< 2^55) operands the column sums reach
    // ~2^116 and a 64-bit carry would truncate
    u64 r0, r1, r2, r3, r4;
    r0 = (u64)t0 & M51; t1 += t0 >> 51;
    r1 = (u64)t1 & M51; t2 += t1 >> 51;
    r2 = (u64)t2 & M51; t3 += t2 >> 51;
    r3 = (u64)t3 & M51; t4 += t3 >> 51;
    r4 = (u64)t4 & M51;
    u128 f = (u128)r0 + (t4 >> 51) * 19;
    r0 = (u64)f & M51;
    r1 += (u64)(f >> 51);
    o->v[0] = r0; o->v[1] = r1; o->v[2] = r2; o->v[3] = r3; o->v[4] = r4;
}

static void fe_sq(fe* o, const fe* a) {
    u64 a0 = a->v[0], a1 = a->v[1], a2 = a->v[2], a3 = a->v[3], a4 = a->v[4];
    u64 d0 = 2 * a0, d1 = 2 * a1, d2 = 2 * a2, d3 = 2 * a3;
    u64 a3_19 = a3 * 19, a4_19 = a4 * 19;
    u128 t0 = (u128)a0 * a0 + (u128)d1 * a4_19 + (u128)d2 * a3_19;
    u128 t1 = (u128)d0 * a1 + (u128)d2 * a4_19 + (u128)a3 * a3_19;
    u128 t2 = (u128)d0 * a2 + (u128)a1 * a1 + (u128)d3 * a4_19;
    u128 t3 = (u128)d0 * a3 + (u128)d1 * a2 + (u128)a4 * a4_19;
    u128 t4 = (u128)d0 * a4 + (u128)d1 * a3 + (u128)a2 * a2;
    u64 r0, r1, r2, r3, r4;
    r0 = (u64)t0 & M51; t1 += t0 >> 51;
    r1 = (u64)t1 & M51; t2 += t1 >> 51;
    r2 = (u64)t2 & M51; t3 += t2 >> 51;
    r3 = (u64)t3 & M51; t4 += t3 >> 51;
    r4 = (u64)t4 & M51;
    u128 f = (u128)r0 + (t4 >> 51) * 19;
    r0 = (u64)f & M51;
    r1 += (u64)(f >> 51);
    o->v[0] = r0; o->v[1] = r1; o->v[2] = r2; o->v[3] = r3; o->v[4] = r4;
}

static void fe_powloop(fe* o, const fe* x, int k) {
    *o = *x;
    for (int i = 0; i < k; i++) fe_sq(o, o);
}

// x^(2^250-1) chain shared by inv / pow22523 / legendre
static void fe_chain250(fe* g, fe* x11, const fe* x) {
    fe t0, t1, t31, a, b, c, d, e, f2;
    fe_sq(&t0, x);                       // x^2
    fe tmp;
    fe_sq(&tmp, &t0); fe_sq(&tmp, &tmp); // x^8
    fe_mul(&t1, x, &tmp);                // x^9
    fe_mul(x11, &t0, &t1);               // x^11
    fe_sq(&tmp, x11);
    fe_mul(&t31, &t1, &tmp);             // x^31 = 2^5-1
    fe_powloop(&tmp, &t31, 5); fe_mul(&a, &tmp, &t31);   // 2^10-1
    fe_powloop(&tmp, &a, 10); fe_mul(&b, &tmp, &a);      // 2^20-1
    fe_powloop(&tmp, &b, 20); fe_mul(&c, &tmp, &b);      // 2^40-1
    fe_powloop(&tmp, &c, 10); fe_mul(&d, &tmp, &a);      // 2^50-1
    fe_powloop(&tmp, &d, 50); fe_mul(&e, &tmp, &d);      // 2^100-1
    fe_powloop(&tmp, &e, 100); fe_mul(&f2, &tmp, &e);    // 2^200-1
    fe_powloop(&tmp, &f2, 50); fe_mul(g, &tmp, &d);      // 2^250-1
}

static void fe_inv(fe* o, const fe* x) {
    fe g, x11, t;
    fe_chain250(&g, &x11, x);
    fe_powloop(&t, &g, 5);
    fe_mul(o, &t, &x11);  // 2^255-21
}

static void fe_pow22523(fe* o, const fe* x) {
    fe g, x11, t;
    fe_chain250(&g, &x11, x);
    fe_powloop(&t, &g, 2);
    fe_mul(o, &t, x);  // 2^252-3
}

static int fe_iszero(const fe* f) {
    u8 b[32];
    fe_tobytes(b, f);
    u8 acc = 0;
    for (int i = 0; i < 32; i++) acc |= b[i];
    return acc == 0;
}

static int fe_eq(const fe* a, const fe* b) {
    u8 x[32], y[32];
    fe_tobytes(x, a);
    fe_tobytes(y, b);
    return memcmp(x, y, 32) == 0;
}

static int fe_isodd(const fe* f) {
    u8 b[32];
    fe_tobytes(b, f);
    return b[0] & 1;
}

static void fe_neg(fe* o, const fe* a) {
    fe z = {{0, 0, 0, 0, 0}};
    fe_sub(o, &z, a);
}

static void fe_set(fe* o, u64 x) {
    o->v[0] = x;
    o->v[1] = o->v[2] = o->v[3] = o->v[4] = 0;
}

// constants
static const u8 K_D[32] = {163,120,89,19,202,77,235,117,171,216,65,65,77,10,112,0,152,232,121,119,121,64,199,140,115,254,111,43,238,108,3,82};
static const u8 K_SQRT_M1[32] = {176,160,14,74,39,27,238,196,120,228,47,173,6,24,67,47,167,215,251,61,153,0,77,43,11,223,193,79,128,36,131,43};
static const u8 K_SQRT_M486664[32] = {6,126,69,255,170,4,110,204,130,26,125,75,209,211,161,197,126,79,252,3,220,8,123,210,187,6,160,96,244,237,38,15};
static const u8 K_BX[32] = {26,213,37,143,96,45,86,201,178,167,37,149,96,199,44,105,92,220,214,253,49,226,164,192,254,83,110,205,211,54,105,33};
static const u8 K_BY[32] = {88,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102};
static const u8 K_L[32] = {237,211,245,92,26,99,18,88,214,156,247,162,222,249,222,20,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,16};

static fe FE_D, FE_SQRT_M1, FE_SQRT_M486664;
static int consts_ready = 0;
static void init_consts();

// sqrt with even-root convention (ops/host/ed25519.fe_sqrt): returns 0 on
// failure, 1 on success
static int fe_sqrt_even(fe* o, const fe* x) {
    init_consts();
    fe r, r2;
    fe_pow22523(&r, x);
    fe x3, x7;  // r = x^((p+3)/8) = x * x^((p-5)/8)?  No: compute directly
    // x^((p+3)/8) = x^(2^252-2) = (x^(2^252-3)) * x
    fe_mul(&r, &r, x);
    fe_sq(&r2, &r);
    if (!fe_eq(&r2, x)) {
        fe_mul(&r, &r, &FE_SQRT_M1);
        fe_sq(&r2, &r);
        if (!fe_eq(&r2, x)) return 0;
    }
    if (fe_isodd(&r)) fe_neg(&r, &r);
    *o = r;
    (void)x3; (void)x7;
    return 1;
}

// legendre symbol via x^((p-1)/2); returns 1 if square or zero
static int fe_issquare(const fe* x) {
    if (fe_iszero(x)) return 1;
    // (p-1)/2 = 2^254 - 10
    fe g, x11, t, x4, x6, acc;
    fe_chain250(&g, &x11, x);      // 2^250-1
    fe_powloop(&t, &g, 4);         // 2^254-16
    fe_sq(&x4, x); fe_sq(&x4, &x4);      // x^4
    fe_sq(&x6, x); fe_mul(&x6, &x4, &x6); // x^6
    fe_mul(&acc, &t, &x6);         // 2^254-10
    fe one;
    fe_set(&one, 1);
    return fe_eq(&acc, &one);
}

// ===========================================================================
// Edwards points (extended coordinates)
// ===========================================================================

struct ge { fe x, y, z, t; };

static ge GE_B;

static void ge_ident(ge* o) {
    fe_set(&o->x, 0);
    fe_set(&o->y, 1);
    fe_set(&o->z, 1);
    fe_set(&o->t, 0);
}

static void init_consts() {
    if (consts_ready) return;
    consts_ready = 1;
    fe_frombytes(&FE_D, K_D);
    fe_frombytes(&FE_SQRT_M1, K_SQRT_M1);
    fe_frombytes(&FE_SQRT_M486664, K_SQRT_M486664);
    fe_frombytes(&GE_B.x, K_BX);
    fe_frombytes(&GE_B.y, K_BY);
    fe_set(&GE_B.z, 1);
    fe_mul(&GE_B.t, &GE_B.x, &GE_B.y);
}

static void ge_add(ge* o, const ge* p, const ge* q) {
    init_consts();
    fe a, b, c, d, e, f, g, h, t0, t1;
    fe_sub(&t0, &p->y, &p->x);
    fe_sub(&t1, &q->y, &q->x);
    fe_mul(&a, &t0, &t1);
    fe_add(&t0, &p->y, &p->x);
    fe_add(&t1, &q->y, &q->x);
    fe_mul(&b, &t0, &t1);
    fe_mul(&c, &p->t, &q->t);
    fe_mul(&c, &c, &FE_D);
    fe_add(&c, &c, &c);
    fe_mul(&d, &p->z, &q->z);
    fe_add(&d, &d, &d);
    fe_sub(&e, &b, &a);
    fe_sub(&f, &d, &c);
    fe_add(&g, &d, &c);
    fe_add(&h, &b, &a);
    fe_mul(&o->x, &e, &f);
    fe_mul(&o->y, &g, &h);
    fe_mul(&o->z, &f, &g);
    fe_mul(&o->t, &e, &h);
}

static void ge_double(ge* o, const ge* p) {
    fe a, b, c, e, f, g, h, t0;
    fe_sq(&a, &p->x);
    fe_sq(&b, &p->y);
    fe_sq(&c, &p->z);
    fe_add(&c, &c, &c);
    fe_add(&h, &a, &b);
    fe_add(&t0, &p->x, &p->y);
    fe_sq(&t0, &t0);
    fe_sub(&e, &h, &t0);
    fe_sub(&g, &a, &b);
    fe_add(&f, &c, &g);
    fe_mul(&o->x, &e, &f);
    fe_mul(&o->y, &g, &h);
    fe_mul(&o->z, &f, &g);
    fe_mul(&o->t, &e, &h);
}

static void ge_neg(ge* o, const ge* p) {
    fe_neg(&o->x, &p->x);
    o->y = p->y;
    o->z = p->z;
    fe_neg(&o->t, &p->t);
}

static int ge_eq(const ge* p, const ge* q) {
    fe a, b;
    fe_mul(&a, &p->x, &q->z);
    fe_mul(&b, &q->x, &p->z);
    if (!fe_eq(&a, &b)) return 0;
    fe_mul(&a, &p->y, &q->z);
    fe_mul(&b, &q->y, &p->z);
    return fe_eq(&a, &b);
}

static void ge_tobytes(u8 b[32], const ge* p) {
    fe zi, x, y;
    fe_inv(&zi, &p->z);
    fe_mul(&x, &p->x, &zi);
    fe_mul(&y, &p->y, &zi);
    fe_tobytes(b, &y);
    b[31] |= (u8)(fe_isodd(&x) << 7);
}

// decompress with the exact ops/host/ed25519.point_decompress semantics:
// reject y >= p (non-canonical), non-residue, x=0 with sign bit
static int ge_frombytes(ge* o, const u8 b[32]) {
    init_consts();
    int sign = b[31] >> 7;
    u8 yb[32];
    memcpy(yb, b, 32);
    yb[31] &= 0x7F;
    // canonical check: y < p
    u8 canon[32];
    fe ytmp;
    fe_frombytes(&ytmp, yb);
    fe_tobytes(canon, &ytmp);
    if (memcmp(canon, yb, 32) != 0) return 0;
    fe y = ytmp, y2, num, den, x;
    fe one;
    fe_set(&one, 1);
    fe_sq(&y2, &y);
    fe_sub(&num, &y2, &one);
    fe_mul(&den, &y2, &FE_D);
    fe_add(&den, &den, &one);
    // x = sqrt(num/den): r = num * den^3 * (num * den^7)^((p-5)/8)
    fe den2, den3, den7, u, r, r2, chk;
    fe_sq(&den2, &den);
    fe_mul(&den3, &den2, &den);
    fe_mul(&den7, &den3, &den2);
    fe_mul(&den7, &den7, &den2);
    fe_mul(&u, &num, &den7);
    fe_pow22523(&r, &u);
    fe_mul(&r, &r, &num);
    fe_mul(&r, &r, &den3);
    fe_sq(&r2, &r);
    fe_mul(&chk, &r2, &den);
    if (!fe_eq(&chk, &num)) {
        fe negnum;
        fe_neg(&negnum, &num);
        if (!fe_eq(&chk, &negnum)) return 0;
        fe_mul(&r, &r, &FE_SQRT_M1);
    }
    x = r;
    int xz = fe_iszero(&x);
    if (xz && sign) return 0;
    if (!xz && fe_isodd(&x) != sign) fe_neg(&x, &x);
    o->x = x;
    o->y = y;
    fe_set(&o->z, 1);
    fe_mul(&o->t, &x, &y);
    return 1;
}

// variable-base scalar mult, 4-bit windows (scalar: 32 LE bytes)
static void ge_scalarmult(ge* o, const u8 s[32], const ge* p) {
    ge tbl[16];
    ge_ident(&tbl[0]);
    tbl[1] = *p;
    for (int i = 2; i < 16; i++) ge_add(&tbl[i], &tbl[i - 1], p);
    ge q;
    ge_ident(&q);
    for (int i = 63; i >= 0; i--) {
        for (int k = 0; k < 4; k++) ge_double(&q, &q);
        int d = (s[i / 2] >> (4 * ((i & 1)))) & 0xF;
        if (d) ge_add(&q, &q, &tbl[d]);
    }
    *o = q;
}

// a*P + b*Q with one shared doubling chain (Strauss, 4-bit windows)
static void ge_double_scalarmult(ge* o, const u8 a[32], const ge* p,
                                 const u8 b[32], const ge* q) {
    ge tp[16], tq[16];
    ge_ident(&tp[0]);
    tp[1] = *p;
    for (int i = 2; i < 16; i++) ge_add(&tp[i], &tp[i - 1], p);
    ge_ident(&tq[0]);
    tq[1] = *q;
    for (int i = 2; i < 16; i++) ge_add(&tq[i], &tq[i - 1], q);
    ge r;
    ge_ident(&r);
    for (int i = 63; i >= 0; i--) {
        for (int k = 0; k < 4; k++) ge_double(&r, &r);
        int da = (a[i / 2] >> (4 * (i & 1))) & 0xF;
        int db = (b[i / 2] >> (4 * (i & 1))) & 0xF;
        if (da) ge_add(&r, &r, &tp[da]);
        if (db) ge_add(&r, &r, &tq[db]);
    }
    *o = r;
}

static void ge_scalarmult_small(ge* o, u64 k, const ge* p) {
    ge q;
    ge_ident(&q);
    ge base = *p;
    while (k) {
        if (k & 1) ge_add(&q, &q, &base);
        ge_double(&base, &base);
        k >>= 1;
    }
    *o = q;
}

// ===========================================================================
// Scalars mod L
// ===========================================================================

// 320-bit accumulator as 5x64
struct sc320 { u64 v[5]; };

static int sc_geq(const sc320* a, const sc320* b) {
    for (int i = 4; i >= 0; i--) {
        if (a->v[i] != b->v[i]) return a->v[i] > b->v[i];
    }
    return 1;
}

static void sc_sub(sc320* a, const sc320* b) {
    u64 borrow = 0;
    for (int i = 0; i < 5; i++) {
        u64 bi = b->v[i] + borrow;
        borrow = (bi < borrow) || (a->v[i] < bi);
        a->v[i] = a->v[i] - bi;
    }
}

static void sc_shl(sc320* a, int k) {  // k < 64
    if (!k) return;
    for (int i = 4; i > 0; i--)
        a->v[i] = (a->v[i] << k) | (a->v[i - 1] >> (64 - k));
    a->v[0] <<= k;
}

// r = bytes (LE, any length) mod L -> 32 LE bytes
static void sc_reduce(u8 out[32], const u8* in, size_t len) {
    sc320 L = {{0}};
    for (int i = 0; i < 32; i++) L.v[i / 8] |= (u64)K_L[i] << (8 * (i % 8));
    sc320 r = {{0}};
    for (size_t i = 0; i < len; i++) {
        // r = r*256 + in[len-1-i]
        sc_shl(&r, 8);
        r.v[0] |= in[len - 1 - i];
        // r < 256*L < 2^261: subtract L<<k for k = 8..0
        for (int k = 8; k >= 0; k--) {
            sc320 lk = L;
            sc_shl(&lk, k);
            if (sc_geq(&r, &lk)) sc_sub(&r, &lk);
        }
    }
    for (int i = 0; i < 32; i++) out[i] = (u8)(r.v[i / 8] >> (8 * (i % 8)));
}

static int sc_is_canonical(const u8 s[32]) {
    for (int i = 31; i >= 0; i--) {
        if (s[i] != K_L[i]) return s[i] < K_L[i];
    }
    return 0;  // s == L
}

// ===========================================================================
// Ed25519 verify (cofactorless) — mirrors ops/host/ed25519.verify
// ===========================================================================

extern "C" int oc_ed25519_verify(const u8 pk[32], const u8 sig[64],
                                 const u8* msg, size_t len) {
    init_consts();
    ge A, R;
    if (!ge_frombytes(&A, pk)) return 0;
    if (!ge_frombytes(&R, sig)) return 0;
    if (!sc_is_canonical(sig + 32)) return 0;
    Sha512 h;
    h.init();
    h.update(sig, 32);
    h.update(pk, 32);
    h.update(msg, len);
    u8 digest[64], hred[32];
    h.final(digest);
    sc_reduce(hred, digest, 64);
    // s*B - h*A must equal R (shared-doubling Strauss with -A)
    ge nA, P;
    ge_neg(&nA, &A);
    ge_double_scalarmult(&P, sig + 32, &GE_B, hred, &nA);
    return ge_eq(&P, &R);
}

// ===========================================================================
// ECVRF draft-03 verify — mirrors ops/host/ecvrf.py
// ===========================================================================

static const u8 VRF_SUITE = 0x04;
static const u64 MONT_A = 486662;

static void elligator2(ge* o, const fe* r) {
    init_consts();
    fe one, monta, t, denom, u, w, u2, tmp;
    fe_set(&one, 1);
    fe_set(&monta, MONT_A);
    fe_sq(&t, r);
    fe_add(&t, &t, &t);  // 2r^2
    fe_add(&denom, &t, &one);
    if (fe_iszero(&denom)) fe_set(&denom, 1);
    fe_inv(&tmp, &denom);
    fe_mul(&u, &monta, &tmp);
    fe_neg(&u, &u);  // -A/(1+2r^2)
    // w = u(u^2+Au+1)
    fe_sq(&w, &u);
    fe_mul(&tmp, &monta, &u);
    fe_add(&w, &w, &tmp);
    fe_add(&w, &w, &one);
    fe_mul(&w, &w, &u);
    if (!fe_issquare(&w)) {
        fe_neg(&u2, &u);
        fe_sub(&u, &u2, &monta);  // -u - A
        fe_sq(&w, &u);
        fe_mul(&tmp, &monta, &u);
        fe_add(&w, &w, &tmp);
        fe_add(&w, &w, &one);
        fe_mul(&w, &w, &u);
    }
    fe v, x, y, up1;
    int ok = fe_sqrt_even(&v, &w);
    (void)ok;  // w is square by construction
    if (fe_iszero(&v)) {
        fe_set(&x, 0);
    } else {
        fe_inv(&tmp, &v);
        fe_mul(&x, &FE_SQRT_M486664, &u);
        fe_mul(&x, &x, &tmp);
    }
    fe_add(&up1, &u, &one);
    if (fe_iszero(&up1)) {
        fe_set(&y, 0);
    } else {
        fe_inv(&tmp, &up1);
        fe_sub(&y, &u, &one);
        fe_mul(&y, &y, &tmp);
    }
    if (fe_isodd(&x)) fe_neg(&x, &x);
    o->x = x;
    o->y = y;
    fe_set(&o->z, 1);
    fe_mul(&o->t, &x, &y);
}

static void vrf_hash_to_curve(ge* o, const u8 pk[32], const u8* alpha,
                              size_t alen) {
    Sha512 h;
    h.init();
    u8 pre[2] = {VRF_SUITE, 0x01};
    h.update(pre, 2);
    h.update(pk, 32);
    h.update(alpha, alen);
    u8 d[64];
    h.final(d);
    u8 rb[32];
    memcpy(rb, d, 32);
    rb[31] &= 0x7F;
    fe r;
    fe_frombytes(&r, rb);  // < 2^255; elligator works mod p
    ge e;
    elligator2(&e, &r);
    ge_double(&e, &e);
    ge_double(&e, &e);
    ge_double(&e, &e);  // *8
    *o = e;
}

// returns 1 and writes beta[64] on success
extern "C" int oc_ecvrf_verify(const u8 pk[32], const u8 pi[80],
                               const u8* alpha, size_t alen, u8 beta[64]) {
    init_consts();
    ge Y, Gamma;
    if (!ge_frombytes(&Y, pk)) return 0;
    if (!ge_frombytes(&Gamma, pi)) return 0;
    const u8* c16 = pi + 32;
    const u8* s32 = pi + 48;
    if (!sc_is_canonical(s32)) return 0;
    ge H;
    vrf_hash_to_curve(&H, pk, alpha, alen);
    u8 c32[32] = {0};
    memcpy(c32, c16, 16);
    ge U, V, nY, nG;
    ge_neg(&nY, &Y);
    ge_double_scalarmult(&U, s32, &GE_B, c32, &nY);
    ge_neg(&nG, &Gamma);
    ge_double_scalarmult(&V, s32, &H, c32, &nG);
    u8 henc[32], genc[32], uenc[32], venc[32];
    ge_tobytes(henc, &H);
    ge_tobytes(genc, &Gamma);
    ge_tobytes(uenc, &U);
    ge_tobytes(venc, &V);
    Sha512 ch;
    ch.init();
    u8 pre[2] = {VRF_SUITE, 0x02};
    ch.update(pre, 2);
    ch.update(henc, 32);
    ch.update(genc, 32);
    ch.update(uenc, 32);
    ch.update(venc, 32);
    u8 cd[64];
    ch.final(cd);
    if (memcmp(cd, c16, 16) != 0) return 0;
    ge G8;
    ge_double(&G8, &Gamma);
    ge_double(&G8, &G8);
    ge_double(&G8, &G8);
    u8 g8enc[32];
    ge_tobytes(g8enc, &G8);
    Sha512 bh;
    bh.init();
    u8 pre3[2] = {VRF_SUITE, 0x03};
    bh.update(pre3, 2);
    bh.update(g8enc, 32);
    bh.final(beta);
    return 1;
}

// Batch-compatible ECVRF (PraosBatchCompat shape): pi = Gamma || U || V || s
// (128 bytes). The challenge is DERIVED from the announced U, V and the two
// group equations are checked — mirrors ops/host/ecvrf.verify_batch_compat.
extern "C" int oc_ecvrf_verify_bc(const u8 pk[32], const u8 pi[128],
                                  const u8* alpha, size_t alen, u8 beta[64]) {
    init_consts();
    ge Y, Gamma, U, V;
    if (!ge_frombytes(&Y, pk)) return 0;
    if (!ge_frombytes(&Gamma, pi)) return 0;
    if (!ge_frombytes(&U, pi + 32)) return 0;
    if (!ge_frombytes(&V, pi + 64)) return 0;
    const u8* s32 = pi + 96;
    if (!sc_is_canonical(s32)) return 0;
    ge H;
    vrf_hash_to_curve(&H, pk, alpha, alen);
    u8 henc[32];
    ge_tobytes(henc, &H);
    Sha512 ch;
    ch.init();
    u8 pre[2] = {VRF_SUITE, 0x02};
    ch.update(pre, 2);
    ch.update(henc, 32);
    ch.update(pi, 96);  // Gamma || U || V announced bytes
    u8 cd[64];
    ch.final(cd);
    u8 c32[32] = {0};
    memcpy(c32, cd, 16);
    // s*B - c*Y must equal U; s*H - c*Gamma must equal V
    ge nY, nG, P;
    ge_neg(&nY, &Y);
    ge_double_scalarmult(&P, s32, &GE_B, c32, &nY);
    if (!ge_eq(&P, &U)) return 0;
    ge_neg(&nG, &Gamma);
    ge_double_scalarmult(&P, s32, &H, c32, &nG);
    if (!ge_eq(&P, &V)) return 0;
    ge G8;
    ge_double(&G8, &Gamma);
    ge_double(&G8, &G8);
    ge_double(&G8, &G8);
    u8 g8enc[32];
    ge_tobytes(g8enc, &G8);
    Sha512 bh;
    bh.init();
    u8 pre3[2] = {VRF_SUITE, 0x03};
    bh.update(pre3, 2);
    bh.update(g8enc, 32);
    bh.final(beta);
    return 1;
}

// ===========================================================================
// CompactSum KES verify — mirrors ops/host/kes.py
// ===========================================================================

extern "C" int oc_kes_verify(const u8 vk[32], int depth, u64 period,
                             const u8* msg, size_t len, const u8* sig,
                             size_t siglen) {
    if (depth < 0 || depth > 20) return 0;
    size_t expect = 96 + 32 * (size_t)depth;
    if (siglen != expect) return 0;
    if (period >= (1ULL << depth)) return 0;
    const u8* ed_sig = sig;
    const u8* vk_leaf = sig + 64;
    if (!oc_ed25519_verify(vk_leaf, ed_sig, msg, len)) return 0;
    u8 cur[32];
    memcpy(cur, vk_leaf, 32);
    for (int i = 0; i < depth; i++) {
        const u8* sib = sig + 96 + 32 * i;
        u8 data[64];
        if ((period >> i) & 1) {
            memcpy(data, sib, 32);
            memcpy(data + 32, cur, 32);
        } else {
            memcpy(data, cur, 32);
            memcpy(data + 32, sib, 32);
        }
        blake2b(data, 64, cur, 32);
    }
    return memcmp(cur, vk, 32) == 0;
}

// ===========================================================================
// Hash helpers + the Praos per-header fold driver
// ===========================================================================

extern "C" void oc_sha512(const u8* p, size_t n, u8 out[64]) { sha512(p, n, out); }
extern "C" void oc_blake2b(const u8* p, size_t n, u8* out, int outlen) {
    blake2b(p, n, out, outlen);
}

// ---------------------------------------------------------------------------
// CRC32 (zlib polynomial 0xEDB88320, reflected) — the sidecar probe's
// seal check. PCLMULQDQ 4-way folding where the CPU has it (runtime
// detected; ~10x zlib's slicing tables), slicing-by-8 otherwise. Both
// produce values bit-identical to zlib.crc32 — the seals on disk were
// written with zlib and MUST keep verifying.
// ---------------------------------------------------------------------------

static uint32_t crc32_tab[8][256];
static int crc32_tab_ready = 0;

static void crc32_tab_init(void) {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c >> 1) ^ (0xEDB88320u & (0u - (c & 1u)));
        crc32_tab[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
        for (int j = 1; j < 8; j++)
            crc32_tab[j][i] = (crc32_tab[j - 1][i] >> 8)
                ^ crc32_tab[0][crc32_tab[j - 1][i] & 0xffu];
    crc32_tab_ready = 1;
}

static uint32_t crc32_sw(const u8* p, size_t n, uint32_t crc) {
    if (!crc32_tab_ready) crc32_tab_init();
    crc = ~crc;
    while (n && ((uintptr_t)p & 7)) {
        crc = (crc >> 8) ^ crc32_tab[0][(crc ^ *p++) & 0xffu];
        n--;
    }
    while (n >= 8) {
        u64 v;
        memcpy(&v, p, 8);
        v ^= crc;
        crc = crc32_tab[7][v & 0xff] ^ crc32_tab[6][(v >> 8) & 0xff]
            ^ crc32_tab[5][(v >> 16) & 0xff] ^ crc32_tab[4][(v >> 24) & 0xff]
            ^ crc32_tab[3][(v >> 32) & 0xff] ^ crc32_tab[2][(v >> 40) & 0xff]
            ^ crc32_tab[1][(v >> 48) & 0xff] ^ crc32_tab[0][(v >> 56) & 0xff];
        p += 8;
        n -= 8;
    }
    while (n--) crc = (crc >> 8) ^ crc32_tab[0][(crc ^ *p++) & 0xffu];
    return ~crc;
}

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>

// Reflected CRC-32 by 4x128-bit carry-less folding (the classic Intel
// PCLMULQDQ scheme; constants are x^K mod P for the zlib polynomial).
// Takes and returns the RAW (pre/post-inverted) crc register; requires
// len >= 64 and len % 16 == 0 — the caller folds the tail with tables.
__attribute__((target("pclmul,sse4.1")))
static uint32_t crc32_clmul(const u8* buf, size_t len, uint32_t crc) {
    const __m128i k1k2 = _mm_set_epi64x(0x00000001c6e41596ll,
                                        0x0000000154442bd4ll);
    const __m128i k3k4 = _mm_set_epi64x(0x00000000ccaa009ell,
                                        0x00000001751997d0ll);
    const __m128i k5k0 = _mm_set_epi64x(0x0000000000000000ll,
                                        0x0000000163cd6124ll);
    const __m128i poly = _mm_set_epi64x(0x00000001f7011641ll,
                                        0x00000001db710641ll);
    __m128i x0, x1, x2, x3, x4, x5, x6, x7, x8, y5, y6, y7, y8;

    x1 = _mm_loadu_si128((const __m128i*)(buf + 0x00));
    x2 = _mm_loadu_si128((const __m128i*)(buf + 0x10));
    x3 = _mm_loadu_si128((const __m128i*)(buf + 0x20));
    x4 = _mm_loadu_si128((const __m128i*)(buf + 0x30));
    x1 = _mm_xor_si128(x1, _mm_cvtsi32_si128((int)crc));
    x0 = k1k2;
    buf += 64;
    len -= 64;

    while (len >= 64) {
        x5 = _mm_clmulepi64_si128(x1, x0, 0x00);
        x6 = _mm_clmulepi64_si128(x2, x0, 0x00);
        x7 = _mm_clmulepi64_si128(x3, x0, 0x00);
        x8 = _mm_clmulepi64_si128(x4, x0, 0x00);
        x1 = _mm_clmulepi64_si128(x1, x0, 0x11);
        x2 = _mm_clmulepi64_si128(x2, x0, 0x11);
        x3 = _mm_clmulepi64_si128(x3, x0, 0x11);
        x4 = _mm_clmulepi64_si128(x4, x0, 0x11);
        y5 = _mm_loadu_si128((const __m128i*)(buf + 0x00));
        y6 = _mm_loadu_si128((const __m128i*)(buf + 0x10));
        y7 = _mm_loadu_si128((const __m128i*)(buf + 0x20));
        y8 = _mm_loadu_si128((const __m128i*)(buf + 0x30));
        x1 = _mm_xor_si128(_mm_xor_si128(x1, x5), y5);
        x2 = _mm_xor_si128(_mm_xor_si128(x2, x6), y6);
        x3 = _mm_xor_si128(_mm_xor_si128(x3, x7), y7);
        x4 = _mm_xor_si128(_mm_xor_si128(x4, x8), y8);
        buf += 64;
        len -= 64;
    }

    // fold the four lanes down to one
    x0 = k3k4;
    x5 = _mm_clmulepi64_si128(x1, x0, 0x00);
    x1 = _mm_clmulepi64_si128(x1, x0, 0x11);
    x1 = _mm_xor_si128(_mm_xor_si128(x1, x2), x5);
    x5 = _mm_clmulepi64_si128(x1, x0, 0x00);
    x1 = _mm_clmulepi64_si128(x1, x0, 0x11);
    x1 = _mm_xor_si128(_mm_xor_si128(x1, x3), x5);
    x5 = _mm_clmulepi64_si128(x1, x0, 0x00);
    x1 = _mm_clmulepi64_si128(x1, x0, 0x11);
    x1 = _mm_xor_si128(_mm_xor_si128(x1, x4), x5);

    while (len >= 16) {
        x2 = _mm_loadu_si128((const __m128i*)buf);
        x5 = _mm_clmulepi64_si128(x1, x0, 0x00);
        x1 = _mm_clmulepi64_si128(x1, x0, 0x11);
        x1 = _mm_xor_si128(_mm_xor_si128(x1, x2), x5);
        buf += 16;
        len -= 16;
    }

    // 128 -> 64 -> 32 reduction, then Barrett
    x2 = _mm_clmulepi64_si128(x1, x0, 0x10);
    x3 = _mm_setr_epi32(~0, 0, ~0, 0);
    x1 = _mm_srli_si128(x1, 8);
    x1 = _mm_xor_si128(x1, x2);

    x0 = k5k0;
    x2 = _mm_srli_si128(x1, 4);
    x1 = _mm_and_si128(x1, x3);
    x1 = _mm_clmulepi64_si128(x1, x0, 0x00);
    x1 = _mm_xor_si128(x1, x2);

    x0 = poly;
    x2 = _mm_and_si128(x1, x3);
    x2 = _mm_clmulepi64_si128(x2, x0, 0x10);
    x2 = _mm_and_si128(x2, x3);
    x2 = _mm_clmulepi64_si128(x2, x0, 0x00);
    x1 = _mm_xor_si128(x1, x2);

    return (uint32_t)_mm_extract_epi32(x1, 1);
}
#endif

extern "C" uint32_t oc_crc32(const u8* p, size_t n, uint32_t crc) {
#if defined(__x86_64__) || defined(__i386__)
    if (n >= 64 && __builtin_cpu_supports("pclmul")
            && __builtin_cpu_supports("sse4.1")) {
        size_t chunk = n & ~(size_t)15;
        crc = ~crc32_clmul(p, chunk, ~crc);
        p += chunk;
        n -= chunk;
    }
#endif
    return crc32_sw(p, n, crc);
}

// Batch blake2b over n spans data[starts[i]:ends[i]) → out[i*outlen ..).
// The columnar-sidecar body-hash sweep: one C loop over the whole chunk
// instead of n Python-side hashlib round-trips.
extern "C" void oc_blake2b_spans(const u8* data, long n,
                                 const long long* starts,
                                 const long long* ends, u8* out,
                                 int outlen) {
    for (long i = 0; i < n; i++) {
        long long s = starts[i], e = ends[i];
        if (e < s) e = s;
        blake2b(data + s, (size_t)(e - s), out + (size_t)i * outlen, outlen);
    }
}

// The full per-header crypto of Praos updateChainDepState
// (Praos.hs:441-606): OCert DSIGN verify + CompactSum KES verify + ECVRF
// verify + declared-output compare. State bookkeeping (nonces, counters,
// leader threshold rationals) stays in the Python fold — it is O(ns) per
// header next to ~0.5ms of crypto. Returns the first failing header
// index (with *fail_kind in {1:ocert, 2:kes, 3:vrf}), or -1 when all n
// verify. Emits per-header blake2b("L" ‖ beta) leader values and the
// vrfNonceValue eta = blake2b(blake2b("N" ‖ beta)) for the nonce fold
// (Praos/VRF.hs:103,116).
// v2: vrf_proof_len selects the proof format (80 = draft-03, 128 =
// batch-compatible); oc_validate_praos below keeps the original 80-byte ABI.
extern "C" long oc_validate_praos2(
    long n,
    const u8* cold_vk,        // n*32
    const u8* ocert_sig,      // n*64
    const u8* ocert_msg,      // n*48 (vk_hot || counter_be8 || period_be8)
    const u8* kes_vk,         // n*32
    const long* kes_t,        // n (evolution = period(slot) - c0)
    const u8* kes_sig,        // n*kes_siglen
    long kes_depth,
    const u8* body,           // flattened signed_bytes
    const long* body_off,     // n+1
    const u8* vrf_vk,         // n*32
    const u8* vrf_proof,      // n*vrf_proof_len
    long vrf_proof_len,       // 80 (draft-03) or 128 (batch-compatible)
    const u8* vrf_alpha,      // n*32
    const u8* vrf_output,     // n*64 (declared beta)
    u8* leader_value,         // out: n*32 blake2b("L" || beta), or NULL
    u8* eta_out,              // out: n*32 vrfNonceValue, or NULL
    long* fail_kind           // out: failure class at the returned index
) {
    size_t kes_siglen = 96 + 32 * (size_t)kes_depth;
    if (fail_kind) *fail_kind = 0;
    if (vrf_proof_len != 80 && vrf_proof_len != 128) {
        if (fail_kind) *fail_kind = 3;
        return n ? 0 : -1;
    }
    for (long i = 0; i < n; i++) {
        if (!oc_ed25519_verify(cold_vk + 32 * i, ocert_sig + 64 * i,
                               ocert_msg + 48 * i, 48)) {
            if (fail_kind) *fail_kind = 1;
            return i;
        }
        const u8* b = body + body_off[i];
        size_t blen = (size_t)(body_off[i + 1] - body_off[i]);
        if (!oc_kes_verify(kes_vk + 32 * i, (int)kes_depth, (u64)kes_t[i], b,
                           blen, kes_sig + kes_siglen * i, kes_siglen)) {
            if (fail_kind) *fail_kind = 2;
            return i;
        }
        u8 beta[64];
        const u8* pi = vrf_proof + vrf_proof_len * i;
        int vrf_ok = (vrf_proof_len == 128)
            ? oc_ecvrf_verify_bc(vrf_vk + 32 * i, pi, vrf_alpha + 32 * i, 32,
                                 beta)
            : oc_ecvrf_verify(vrf_vk + 32 * i, pi, vrf_alpha + 32 * i, 32,
                              beta);
        if (!vrf_ok || memcmp(beta, vrf_output + 64 * i, 64) != 0) {
            if (fail_kind) *fail_kind = 3;
            return i;
        }
        if (leader_value) {
            u8 lin[65];
            lin[0] = 'L';
            memcpy(lin + 1, beta, 64);
            blake2b(lin, 65, leader_value + 32 * i, 32);
        }
        if (eta_out) {
            u8 nin[65], eta1[32];
            nin[0] = 'N';
            memcpy(nin + 1, beta, 64);
            blake2b(nin, 65, eta1, 32);
            blake2b(eta1, 32, eta_out + 32 * i, 32);
        }
    }
    return -1;
}

// legacy ABI: fixed 80-byte draft-03 proofs
extern "C" long oc_validate_praos(
    long n, const u8* cold_vk, const u8* ocert_sig, const u8* ocert_msg,
    const u8* kes_vk, const long* kes_t, const u8* kes_sig, long kes_depth,
    const u8* body, const long* body_off, const u8* vrf_vk,
    const u8* vrf_proof, const u8* vrf_alpha, const u8* vrf_output,
    u8* leader_value, u8* eta_out, long* fail_kind) {
    return oc_validate_praos2(
        n, cold_vk, ocert_sig, ocert_msg, kes_vk, kes_t, kes_sig, kes_depth,
        body, body_off, vrf_vk, vrf_proof, 80, vrf_alpha, vrf_output,
        leader_value, eta_out, fail_kind);
}

// ===========================================================================
// Debug/test exports (differential testing of the internals)
// ===========================================================================

extern "C" void oc_fe_test(const u8 a[32], const u8 b[32], u8 mul_out[32],
                           u8 chain_out[32], u8 inv_out[32], u8 sqrt_out[32],
                           int* sqrt_ok, int* issq) {
    fe fa, fb, fm, t1, t2, t3, fi, fs;
    fe_frombytes(&fa, a);
    fe_frombytes(&fb, b);
    fe_mul(&fm, &fa, &fb);
    fe_tobytes(mul_out, &fm);
    // lazy chain: ((a+b)*(a-b) + a*a) doubled, squared
    fe_add(&t1, &fa, &fb);
    fe_sub(&t2, &fa, &fb);
    fe_mul(&t3, &t1, &t2);
    fe sq;
    fe_sq(&sq, &fa);
    fe_add(&t3, &t3, &sq);
    fe_add(&t3, &t3, &t3);
    fe_sq(&t3, &t3);
    fe_tobytes(chain_out, &t3);
    fe_inv(&fi, &fa);
    fe_tobytes(inv_out, &fi);
    *sqrt_ok = fe_sqrt_even(&fs, &fa);
    fe_tobytes(sqrt_out, &fs);
    *issq = fe_issquare(&fa);
}

extern "C" int oc_ge_test(const u8 enc[32], const u8 s[32], u8 rt_out[32],
                          u8 mul_out[32], u8 dbl_out[32]) {
    ge p, q, d;
    if (!ge_frombytes(&p, enc)) return 0;
    ge_tobytes(rt_out, &p);
    ge_scalarmult(&q, s, &p);
    ge_tobytes(mul_out, &q);
    ge_double(&d, &p);
    ge_tobytes(dbl_out, &d);
    return 1;
}

extern "C" void oc_sc_reduce_test(const u8* in, size_t len, u8 out[32]) {
    sc_reduce(out, in, len);
}

extern "C" int oc_dsmul_test(const u8 a[32], const u8 penc[32], const u8 b[32],
                             const u8 qenc[32], u8 out[32]) {
    ge p, q, r;
    if (!ge_frombytes(&p, penc) || !ge_frombytes(&q, qenc)) return 0;
    ge_double_scalarmult(&r, a, &p, b, &q);
    ge_tobytes(out, &r);
    return 1;
}

// ===========================================================================
// Sign side: Ed25519 sign + ECVRF prove — mirrors ops/host/{ed25519,ecvrf}.py
// (deterministic; byte-identical to the Python reference signers). Used by
// db_synthesizer / fixtures so benchmark chains forge at C speed.
// ===========================================================================

// s_out = (r + c*a) mod L ; all scalars 32-byte LE
static void sc_muladd(u8 s_out[32], const u8 c[32], const u8 a[32],
                      const u8 r[32]) {
    // 512-bit product c*a in 64 LE bytes, + r
    u8 buf[64] = {0};
    uint32_t prod[16] = {0};
    for (int i = 0; i < 8; i++) {
        u64 ci = ((u64)c[4 * i]) | ((u64)c[4 * i + 1] << 8) |
                 ((u64)c[4 * i + 2] << 16) | ((u64)c[4 * i + 3] << 24);
        u64 carry = 0;
        for (int j = 0; j < 8; j++) {
            u64 aj = ((u64)a[4 * j]) | ((u64)a[4 * j + 1] << 8) |
                     ((u64)a[4 * j + 2] << 16) | ((u64)a[4 * j + 3] << 24);
            unsigned __int128 t = (unsigned __int128)ci * aj + prod[i + j] + carry;
            prod[i + j] = (uint32_t)t;
            carry = (u64)(t >> 32);
        }
        int k = i + 8;
        while (carry && k < 16) {
            u64 t = (u64)prod[k] + (carry & 0xFFFFFFFFu);
            prod[k] = (uint32_t)t;
            carry = (carry >> 32) + (t >> 32);
            k++;
        }
    }
    for (int i = 0; i < 16; i++) {
        buf[4 * i] = (u8)prod[i];
        buf[4 * i + 1] = (u8)(prod[i] >> 8);
        buf[4 * i + 2] = (u8)(prod[i] >> 16);
        buf[4 * i + 3] = (u8)(prod[i] >> 24);
    }
    // + r with carry
    uint32_t carry2 = 0;
    for (int i = 0; i < 32; i++) {
        uint32_t t = (uint32_t)buf[i] + r[i] + carry2;
        buf[i] = (u8)t;
        carry2 = t >> 8;
    }
    for (int i = 32; i < 64 && carry2; i++) {
        uint32_t t = (uint32_t)buf[i] + carry2;
        buf[i] = (u8)t;
        carry2 = t >> 8;
    }
    sc_reduce(s_out, buf, 64);
}

static void clamp_scalar(u8 a[32]) {
    a[0] &= 248;
    a[31] &= 127;
    a[31] |= 64;
}

extern "C" void oc_ed25519_public(const u8 seed[32], u8 pk[32]) {
    init_consts();
    u8 h[64];
    sha512(seed, 32, h);
    clamp_scalar(h);
    ge A;
    ge_scalarmult(&A, h, &GE_B);
    ge_tobytes(pk, &A);
}

extern "C" void oc_ed25519_sign(const u8 seed[32], const u8* msg, size_t len,
                                u8 sig[64]) {
    init_consts();
    u8 h[64];
    sha512(seed, 32, h);
    u8 a[32];
    memcpy(a, h, 32);
    clamp_scalar(a);
    ge A;
    ge_scalarmult(&A, a, &GE_B);
    u8 aenc[32];
    ge_tobytes(aenc, &A);
    // r = SHA512(prefix || msg) mod L
    Sha512 hr;
    hr.init();
    hr.update(h + 32, 32);
    hr.update(msg, len);
    u8 rd[64];
    hr.final(rd);
    u8 r[32];
    sc_reduce(r, rd, 64);
    ge R;
    ge_scalarmult(&R, r, &GE_B);
    ge_tobytes(sig, &R);
    // k = SHA512(R || A || msg) mod L ; s = (r + k*a) mod L
    Sha512 hk;
    hk.init();
    hk.update(sig, 32);
    hk.update(aenc, 32);
    hk.update(msg, len);
    u8 kd[64];
    hk.final(kd);
    u8 k[32];
    sc_reduce(k, kd, 64);
    sc_muladd(sig + 32, k, a, r);
}

extern "C" void oc_ecvrf_prove(const u8 seed[32], const u8* alpha, size_t alen,
                               u8 pi[80]) {
    init_consts();
    u8 h[64];
    sha512(seed, 32, h);
    u8 x[32];
    memcpy(x, h, 32);
    clamp_scalar(x);
    ge A;
    ge_scalarmult(&A, x, &GE_B);
    u8 pk[32];
    ge_tobytes(pk, &A);
    ge H;
    vrf_hash_to_curve(&H, pk, alpha, alen);
    u8 henc[32];
    ge_tobytes(henc, &H);
    ge Gamma;
    ge_scalarmult(&Gamma, x, &H);
    // nonce k = SHA512(prefix || H_enc) mod L (draft-03 5.4.2.2)
    Sha512 hn;
    hn.init();
    hn.update(h + 32, 32);
    hn.update(henc, 32);
    u8 nd[64];
    hn.final(nd);
    u8 k[32];
    sc_reduce(k, nd, 64);
    ge U, V;
    ge_scalarmult(&U, k, &GE_B);
    ge_scalarmult(&V, k, &H);
    u8 genc[32], uenc[32], venc[32];
    ge_tobytes(genc, &Gamma);
    ge_tobytes(uenc, &U);
    ge_tobytes(venc, &V);
    Sha512 ch;
    ch.init();
    u8 pre[2] = {VRF_SUITE, 0x02};
    ch.update(pre, 2);
    ch.update(henc, 32);
    ch.update(genc, 32);
    ch.update(uenc, 32);
    ch.update(venc, 32);
    u8 cd[64];
    ch.final(cd);
    u8 c32[32] = {0};
    memcpy(c32, cd, 16);
    memcpy(pi, genc, 32);
    memcpy(pi + 32, cd, 16);
    sc_muladd(pi + 48, c32, x, k);
}

// batch-compatible prove: pi = Gamma || U || V || s (128 bytes); same
// transcript as oc_ecvrf_prove, announced points instead of the challenge
extern "C" void oc_ecvrf_prove_bc(const u8 seed[32], const u8* alpha,
                                  size_t alen, u8 pi[128]) {
    init_consts();
    u8 h[64];
    sha512(seed, 32, h);
    u8 x[32];
    memcpy(x, h, 32);
    clamp_scalar(x);
    ge A;
    ge_scalarmult(&A, x, &GE_B);
    u8 pk[32];
    ge_tobytes(pk, &A);
    ge H;
    vrf_hash_to_curve(&H, pk, alpha, alen);
    u8 henc[32];
    ge_tobytes(henc, &H);
    ge Gamma;
    ge_scalarmult(&Gamma, x, &H);
    Sha512 hn;
    hn.init();
    hn.update(h + 32, 32);
    hn.update(henc, 32);
    u8 nd[64];
    hn.final(nd);
    u8 k[32];
    sc_reduce(k, nd, 64);
    ge U, V;
    ge_scalarmult(&U, k, &GE_B);
    ge_scalarmult(&V, k, &H);
    u8 genc[32], uenc[32], venc[32];
    ge_tobytes(genc, &Gamma);
    ge_tobytes(uenc, &U);
    ge_tobytes(venc, &V);
    Sha512 ch;
    ch.init();
    u8 pre[2] = {VRF_SUITE, 0x02};
    ch.update(pre, 2);
    ch.update(henc, 32);
    ch.update(genc, 32);
    ch.update(uenc, 32);
    ch.update(venc, 32);
    u8 cd[64];
    ch.final(cd);
    u8 c32[32] = {0};
    memcpy(c32, cd, 16);
    memcpy(pi, genc, 32);
    memcpy(pi + 32, uenc, 32);
    memcpy(pi + 64, venc, 32);
    sc_muladd(pi + 96, c32, x, k);
}

extern "C" int oc_ecvrf_proof_to_hash(const u8 pi[80], u8 beta[64]) {
    init_consts();
    ge Gamma;
    if (!ge_frombytes(&Gamma, pi)) return 0;
    ge G8;
    ge_double(&G8, &Gamma);
    ge_double(&G8, &G8);
    ge_double(&G8, &G8);
    u8 g8enc[32];
    ge_tobytes(g8enc, &G8);
    Sha512 bh;
    bh.init();
    u8 pre3[2] = {VRF_SUITE, 0x03};
    bh.update(pre3, 2);
    bh.update(g8enc, 32);
    bh.final(beta);
    return 1;
}
