// Native chunk scanner / columnar header extractor.
//
// The host-side data loader of the framework: parses ImmutableDB chunk
// files (concatenated CBOR blocks, layout defined by
// ouroboros_consensus_tpu/block/praos_block.py) directly into the
// struct-of-arrays columns the device staging layer consumes, without
// materializing Python objects. This is the C++ runtime component the
// reference keeps in external C packages (CBOR decode via cborg,
// libsodium hashing) — CBOR decode throughput is the host bottleneck at
// batch rates (SURVEY.md §7.3 items 5-6).
//
// Block layout (praos_block.py):
//   block  = [header, [tx, ...]]
//   header = [body, kes_sig]
//   body   = [block_no, slot, prev_hash|null, issuer_vk, vrf_vk,
//             [vrf_output, vrf_proof], body_size, body_hash,
//             [ocert_vk, counter, kes_period, sigma], [pv_maj, pv_min]]
//
// The KES-signed message is the body's exact CBOR span, which we return
// as (offset, len) into the chunk buffer — zero copies.
//
// Build: g++ -O2 -shared -fPIC -o libheaderscan.so headerscan.cpp

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

struct Cursor {
    const uint8_t* p;
    size_t len;
    size_t off;
    bool ok;

    bool need(size_t n) {
        if (off + n > len) { ok = false; return false; }
        return true;
    }
    uint8_t peek() { return p[off]; }
    uint8_t take() { return p[off++]; }
};

// Read a CBOR head; returns major in *major and argument in *arg.
bool read_head(Cursor& c, int* major, uint64_t* arg) {
    if (!c.need(1)) return false;
    uint8_t b = c.take();
    *major = b >> 5;
    uint8_t info = b & 0x1f;
    if (info < 24) { *arg = info; return true; }
    int n;
    switch (info) {
        case 24: n = 1; break;
        case 25: n = 2; break;
        case 26: n = 4; break;
        case 27: n = 8; break;
        default: c.ok = false; return false;  // indefinite not emitted
    }
    if (!c.need((size_t)n)) return false;
    uint64_t v = 0;
    for (int i = 0; i < n; i++) v = (v << 8) | c.take();
    *arg = v;
    return true;
}

// Skip one complete CBOR item.
bool skip_item(Cursor& c) {
    int major; uint64_t arg;
    if (!read_head(c, &major, &arg)) return false;
    switch (major) {
        case 0: case 1: return true;                    // ints
        case 2: case 3:                                  // bytes/text
            if (!c.need(arg)) return false;
            c.off += arg; return true;
        case 4:                                          // array
            for (uint64_t i = 0; i < arg; i++)
                if (!skip_item(c)) return false;
            return true;
        case 5:                                          // map
            for (uint64_t i = 0; i < 2 * arg; i++)
                if (!skip_item(c)) return false;
            return true;
        case 6: return skip_item(c);                     // tag
        case 7:                                          // simple/float
            return true;       // read_head already consumed the payload
        default: return false;
    }
}

bool expect_array(Cursor& c, uint64_t* n) {
    int major; uint64_t arg;
    if (!read_head(c, &major, &arg) || major != 4) { c.ok = false; return false; }
    *n = arg;
    return true;
}

bool read_uint(Cursor& c, int64_t* out) {
    int major; uint64_t arg;
    if (!read_head(c, &major, &arg) || major != 0) { c.ok = false; return false; }
    *out = (int64_t)arg;
    return true;
}

// bytes of exactly `want` length copied to dst; or null (-> zero, *present=0)
bool read_bytes_fixed(Cursor& c, uint8_t* dst, size_t want, uint8_t* present) {
    int major; uint64_t arg;
    size_t save = c.off;
    if (!read_head(c, &major, &arg)) return false;
    if (major == 7 && arg == 22) {  // null
        memset(dst, 0, want);
        if (present) *present = 0;
        return true;
    }
    if (major != 2 || arg != want) { c.off = save; c.ok = false; return false; }
    if (!c.need(arg)) return false;
    memcpy(dst, c.p + c.off, want);
    c.off += arg;
    if (present) *present = 1;
    return true;
}

// bytes of one of two allowed lengths (the 80-byte draft-03 vs 128-byte
// batch-compatible VRF proof), copied into a want_max-wide zero-padded
// row; actual length recorded in *len_out
bool read_bytes_either(Cursor& c, uint8_t* dst, size_t want_a,
                       size_t want_b, size_t want_max, int64_t* len_out) {
    int major; uint64_t arg;
    size_t save = c.off;
    if (!read_head(c, &major, &arg)) return false;
    if (major != 2 || (arg != want_a && arg != want_b)) {
        c.off = save; c.ok = false; return false;
    }
    if (!c.need(arg)) return false;
    memset(dst, 0, want_max);
    memcpy(dst, c.p + c.off, arg);
    c.off += arg;
    *len_out = (int64_t)arg;
    return true;
}

// variable-length bytes: record (offset, len), no copy
bool read_bytes_span(Cursor& c, int64_t* off_out, int64_t* len_out) {
    int major; uint64_t arg;
    if (!read_head(c, &major, &arg) || major != 2) { c.ok = false; return false; }
    if (!c.need(arg)) return false;
    *off_out = (int64_t)c.off;
    *len_out = (int64_t)arg;
    c.off += arg;
    return true;
}

}  // namespace

extern "C" {

// Scan concatenated top-level CBOR items; fill offsets/sizes.
// Returns the count of COMPLETE items (stopping at max_items). A torn
// or malformed tail ends the scan: *bad_off is the offset where the
// good prefix ends (== len iff the whole buffer is well-formed) — the
// truncate-corrupted-tail recovery point (ImmutableDB/Impl/Validation).
int ocx_scan_items(const uint8_t* buf, size_t len,
                   int64_t* offsets, int64_t* sizes, int max_items,
                   int64_t* bad_off) {
    Cursor c{buf, len, 0, true};
    int n = 0;
    while (c.off < c.len && n < max_items) {
        size_t start = c.off;
        if (!skip_item(c) || !c.ok) {
            if (bad_off) *bad_off = (int64_t)start;
            return n;
        }
        offsets[n] = (int64_t)start;
        sizes[n] = (int64_t)(c.off - start);
        n++;
    }
    if (bad_off) *bad_off = (int64_t)c.off;
    return n;
}

// Extract header columns from n blocks located at offsets[] in buf.
// Fixed-width outputs are caller-allocated numpy arrays; variable-width
// fields (kes_sig, signed body span) come back as (offset, len) pairs
// into buf. Returns 0 on success, or 1-based index of first bad block.
int ocx_extract_headers(
    const uint8_t* buf, size_t len,
    const int64_t* offsets, int n,
    int64_t* block_no, int64_t* slot,
    uint8_t* prev_hash /* n*32 */, uint8_t* has_prev,
    uint8_t* issuer_vk /* n*32 */, uint8_t* vrf_vk /* n*32 */,
    uint8_t* vrf_output /* n*64 */,
    uint8_t* vrf_proof /* n*128, zero-padded */,
    int64_t* vrf_proof_len /* n: 80 (draft-03) or 128 (batch-compat) */,
    int64_t* body_size, uint8_t* body_hash /* n*32 */,
    uint8_t* ocert_vk /* n*32 */, int64_t* ocert_counter,
    int64_t* ocert_kes_period, int64_t* ocert_sigma_off,
    int64_t* ocert_sigma_len, int64_t* pv_major, int64_t* pv_minor,
    int64_t* kes_sig_off, int64_t* kes_sig_len,
    int64_t* signed_off, int64_t* signed_len) {
    for (int i = 0; i < n; i++) {
        Cursor c{buf, len, (size_t)offsets[i], true};
        uint64_t na;
        // block = [header, txs]
        if (!expect_array(c, &na) || na != 2) return i + 1;
        // header = [body, kes_sig]
        if (!expect_array(c, &na) || na != 2) return i + 1;
        size_t body_start = c.off;
        // body = [...10 fields...]
        if (!expect_array(c, &na) || na != 10) return i + 1;
        if (!read_uint(c, &block_no[i])) return i + 1;
        if (!read_uint(c, &slot[i])) return i + 1;
        if (!read_bytes_fixed(c, prev_hash + 32 * i, 32, &has_prev[i])) return i + 1;
        if (!read_bytes_fixed(c, issuer_vk + 32 * i, 32, nullptr)) return i + 1;
        if (!read_bytes_fixed(c, vrf_vk + 32 * i, 32, nullptr)) return i + 1;
        if (!expect_array(c, &na) || na != 2) return i + 1;
        if (!read_bytes_fixed(c, vrf_output + 64 * i, 64, nullptr)) return i + 1;
        if (!read_bytes_either(c, vrf_proof + 128 * i, 80, 128, 128,
                               &vrf_proof_len[i])) return i + 1;
        if (!read_uint(c, &body_size[i])) return i + 1;
        if (!read_bytes_fixed(c, body_hash + 32 * i, 32, nullptr)) return i + 1;
        if (!expect_array(c, &na) || na != 4) return i + 1;
        if (!read_bytes_fixed(c, ocert_vk + 32 * i, 32, nullptr)) return i + 1;
        if (!read_uint(c, &ocert_counter[i])) return i + 1;
        if (!read_uint(c, &ocert_kes_period[i])) return i + 1;
        if (!read_bytes_span(c, &ocert_sigma_off[i], &ocert_sigma_len[i])) return i + 1;
        if (!expect_array(c, &na) || na != 2) return i + 1;
        if (!read_uint(c, &pv_major[i])) return i + 1;
        if (!read_uint(c, &pv_minor[i])) return i + 1;
        signed_off[i] = (int64_t)body_start;
        signed_len[i] = (int64_t)(c.off - body_start);
        if (!read_bytes_span(c, &kes_sig_off[i], &kes_sig_len[i])) return i + 1;
        // structurally walk the txs item too: the batched integrity
        // check hashes the txs SPAN without decoding it, so a block
        // whose declared body hash covers garbled (non-CBOR) txs bytes
        // must still be rejected here, matching the per-block decode
        // path (Block.from_bytes raises). skip_item is O(#cbor items).
        if (!skip_item(c) || !c.ok) return i + 1;
    }
    return 0;
}

// Batched CRC-32 (ISO-HDLC, the zlib.crc32 polynomial) over n spans of
// buf. Returns the 0-based index of the first span whose CRC differs
// from expected[], or -1 when all match. This is the ImmutableDB deep
// validation hot loop (validate_all at open): per-span Python
// zlib.crc32 calls cost ~25 us of interpreter overhead each, ~2.5 s on
// a 100k-block chain — one native walk is ~50 ms.
static uint32_t crc_table[256];
static bool crc_init_done = [] {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        crc_table[i] = c;
    }
    return true;
}();

int64_t ocx_crc32_first_bad(const uint8_t* buf, size_t len,
                            const int64_t* offsets, const int64_t* sizes,
                            const int64_t* expected, int64_t n) {
    for (int64_t i = 0; i < n; i++) {
        int64_t off = offsets[i], sz = sizes[i];
        // unsigned bounds math: off + sz as int64 is UB for huge values
        // from a corrupt index; each side-checked add is overflow-free
        if (off < 0 || sz < 0 || (uint64_t)off > len ||
            (uint64_t)sz > len - (uint64_t)off)
            return i;
        uint32_t c = 0xFFFFFFFFu;
        const uint8_t* p = buf + off;
        for (int64_t j = 0; j < sz; j++)
            c = crc_table[(c ^ p[j]) & 0xFF] ^ (c >> 8);
        if ((c ^ 0xFFFFFFFFu) != (uint32_t)expected[i]) return i;
    }
    return -1;
}

// Parse a concatenated-CBOR ImmutableDB index: entries are 6-element
// arrays [slot, block_no, hash(32B), offset, size, crc32]. Stops at the
// first malformed/torn entry (crash mid-append just ends the list —
// same contract as the Python loop). Returns the entry count. Python
// index loads cost ~9 us/entry of interpreter + decode overhead — 9 s
// on the 1M-header bench chain's open; this walk is ~20 ms.
int64_t ocx_parse_index(const uint8_t* buf, size_t len, int64_t max_items,
                        int64_t* slot, int64_t* block_no,
                        uint8_t* hash /* n*32 */, int64_t* offset,
                        int64_t* size, int64_t* crc32) {
    Cursor c{buf, len, 0, true};
    int64_t n = 0;
    while (c.off < c.len && n < max_items) {
        uint64_t na;
        Cursor save = c;
        // strict 32-byte hash read: read_bytes_fixed's null-acceptance
        // is a header-parsing (absent prev_hash) concession — an index
        // hash must be exactly bytes(32), like the Python loop's
        // IndexEntry.from_cbor_obj
        int hmaj; uint64_t harg;
        bool ok =
            expect_array(c, &na) && na == 6 &&
            read_uint(c, &slot[n]) && read_uint(c, &block_no[n]) &&
            read_head(c, &hmaj, &harg) && hmaj == 2 && harg == 32 &&
            c.need(32);
        if (ok) {
            memcpy(hash + 32 * n, c.p + c.off, 32);
            c.off += 32;
            ok = read_uint(c, &offset[n]) && read_uint(c, &size[n]) &&
                 read_uint(c, &crc32[n]) && c.ok;
        }
        if (!ok) {
            c = save;
            break;
        }
        n++;
    }
    return n;
}

}  // extern "C"
